"""BASELINE ladder #3 executed AT SHAPE: Sinkhorn-OT soft assignment at
P = T = 100,000 (matrix-free blocked potentials + plan-guided rounding),
with assignment quality compared against the eps-scaled auction on the
SAME instance (VERDICT r4 item 5's done-bar).

The [P, T] tensor would be 40 GB — both pipelines here are streaming
(O(P * tile) peak), and quality is measured pairwise via ops.cost.cost_pairs
for the same reason. Run:

    python scripts/stage_s_100k.py [--cpu]

Emits one JSON line per stage row (consumed by the r5 scaling artifact).
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true", help="force host CPU")
    ap.add_argument("--size", type=int, default=100_000)
    ap.add_argument("--tile", type=int, default=2500)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument(
        "--artifact",
        default="artifacts/stage_s_rows.jsonl",
        help="JSONL file each stage row is APPENDED to as it completes "
        "(a timeout cannot erase finished stages — the r4/r5 artifact "
        "deaths left header-only logs). Empty string disables.",
    )
    args = ap.parse_args()

    from protocol_tpu.utils.artifacts import append_jsonl

    def emit(row: dict) -> None:
        print(json.dumps(row), flush=True)
        append_jsonl(args.artifact, row)

    if args.cpu:
        from protocol_tpu.utils.platform import force_host_cpu

        force_host_cpu(1)
    import jax
    import jax.numpy as jnp
    import numpy as np

    import bench
    from protocol_tpu.ops.blocked import sinkhorn_potentials_blocked
    from protocol_tpu.ops.cost import INFEASIBLE, CostWeights, cost_pairs
    from protocol_tpu.ops.sparse import (
        assign_auction_sparse_scaled,
        candidates_topk_bidir,
    )

    P = T = args.size
    tile = args.tile
    assert T % tile == 0, f"tile {tile} must divide T {T}"
    platform = jax.devices()[0].platform
    weights = CostWeights()
    rng = np.random.default_rng(42)
    print(f"# stage S at shape: P=T={P} tile={tile} platform={platform}",
          file=sys.stderr, flush=True)
    ep = jax.tree.map(jnp.asarray, bench.synth_providers(rng, P))
    er = jax.tree.map(jnp.asarray, bench.synth_requirements(rng, T))

    def quality(p4t) -> dict:
        c = np.asarray(cost_pairs(ep, er, p4t, weights))
        p4t = np.asarray(p4t)
        ok = (p4t >= 0) & (c < INFEASIBLE * 0.5)
        pos = p4t[p4t >= 0]
        return {
            "assigned": int((p4t >= 0).sum()),
            "injective": bool(np.unique(pos).size == pos.size),
            "infeasible_pairs": int((p4t >= 0).sum() - ok.sum()),
            "mean_cost": round(float(c[ok].mean()), 4) if ok.any() else None,
        }

    # ---- Sinkhorn potentials (the OT solve), computed ONCE and fed
    # into the plan-guided rounding directly — assign_sinkhorn_blocked
    # would recompute them, doubling the dominant O(P*T*iters) stage
    # (each iteration is two full [P, T] logsumexp passes: ~1 h/iter at
    # 100k on this 1-core host)
    eps_sink = 0.05
    t0 = time.perf_counter()
    u, v = sinkhorn_potentials_blocked(
        ep, er, weights, eps=eps_sink, num_iters=args.iters, tile=tile
    )
    jax.block_until_ready((u, v))
    t_pot = time.perf_counter() - t0
    print(f"# potentials done: {t_pot:.1f}s", file=sys.stderr, flush=True)

    # plan-guided candidates + auction rounding (the body of
    # ops.blocked.assign_sinkhorn_blocked, with u reused)
    from protocol_tpu.ops.sparse import (
        assign_auction_sparse_scaled as _round_solve,
        candidates_topk,
    )

    t0 = time.perf_counter()
    offset = -eps_sink * jnp.where(u > -5e17, u, 0.0)
    cand_su, cand_sc = candidates_topk(
        ep, er, weights, k=32, tile=tile, provider_offset=offset
    )
    res_s = _round_solve(
        cand_su, cand_sc, num_providers=P, eps_start=1.0, eps_end=0.02
    )
    jax.block_until_ready(res_s.provider_for_task)
    t_sink = t_pot + (time.perf_counter() - t0)
    q_sink = quality(res_s.provider_for_task)
    emit({
        "stage": "S sinkhorn-OT at shape (measured)",
        "platform": platform,
        "shape": f"P=T={P} iters={args.iters} tile={tile} (potentials reused for rounding)",
        "potentials_s": round(t_pot, 2),
        "end_to_end_s": round(t_sink, 2),
        **{f"sinkhorn_{k}": v for k, v in q_sink.items()},
    })

    # ---- the auction on the SAME instance (quality referee) ----
    t0 = time.perf_counter()
    cp, cc = candidates_topk_bidir(
        ep, er, weights, k=64, tile=tile, reverse_r=8, extra=16
    )
    jax.block_until_ready((cp, cc))
    t_gen = time.perf_counter() - t0
    t0 = time.perf_counter()
    res_a = assign_auction_sparse_scaled(
        cp, cc, num_providers=P, frontier=8192
    )
    jax.block_until_ready(res_a.provider_for_task)
    t_solve = time.perf_counter() - t0
    q_auc = quality(res_a.provider_for_task)
    emit({
        "stage": "S auction referee on the same instance (measured)",
        "platform": platform,
        "shape": f"P=T={P} k=64 bidir",
        "gen_s": round(t_gen, 2),
        "solve_s": round(t_solve, 2),
        **{f"auction_{k}": v for k, v in q_auc.items()},
    })


if __name__ == "__main__":
    main()
