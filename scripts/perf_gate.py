#!/usr/bin/env python
"""CI perf floor for the native assignment engine (VERDICT r5 "what's
missing" #4: a solver regression like round 4's 0.2x warm bug would merge
clean without a bench gate).

Runs a small (2k x 2k) native-engine solve and FAILS (exit 1) when:

  - end-to-end throughput drops below the stored floor
    (scripts/perf_floor.json — conservative: ~25% of the slowest
    observed CI-class 2-core host, so machine jitter never false-fails
    while a 4x regression cannot merge), or
  - parity vs the greedy oracle breaks: the auction must assign at least
    as many tasks as greedy and at no more than 102% of greedy's total
    cost on its own candidate surface, or
  - the multi-threaded engine's matching is not bit-identical to
    threads=1 (the -mt determinism contract).

With ``--wire`` it instead runs the loopback WIRE-PATH floor (ISSUE 2):
a 16k x 16k marketplace with 1% row churn over a real localhost gRPC
seam — the v2 delta tick (serialize + RPC + warm native-mt solve) must
beat the v1 full-snapshot tick by >= 3x end-to-end with >= 20x fewer
per-tick wire bytes, and the steady-state matching must keep >= 97% of
tasks assigned. A wire regression (a chatty codec, a session-protocol
break, a warm-solve regression behind the seam) cannot merge on green
unit tests alone.

With ``--sinkhorn`` it runs the sparse-Sinkhorn engine smoke (ISSUE 3):
a 4k x 4k parity + quality gate — the sinkhorn-mt potentials must be
bit-identical between threads=1 and threads=2, the auction-referee
rounding must assign >= 97% of what the plain auction assigns on the
same candidate structure at <= 102% of its mean cost — plus the 16k x 16k
warm-potential-carry floor: a 1% churn warm re-solve through the sinkhorn
arena must be >= 2x faster than the cold solve. A solver or warm-carry
regression cannot merge on green unit tests alone.

With ``--trace`` it runs the golden-trace replay gate (ISSUE 5): the
committed flight-recorder trace (artifacts/golden_trace_512x512.trace)
replayed through native-mt at threads {1, 2} and through the v2 wire
loopback must reproduce the recorded assignments BIT-FOR-BIT (empty
divergence report), the steady-state assigned fraction must hold, and
the warm ticks must beat the cold tick by the stored floor — so a
solver, codec, or warm-path regression shows up as a named divergent
tick/row set, not a vague bench delta.

With ``--obs`` it runs the observability-overhead gate (ISSUE 6): a 4k
arena chain (cold + warm churn + short-circuit tick) with spans +
native EngineStats ON must stay within ``obs_overhead_max_frac`` of the
same chain with the plane OFF (paired alternating runs, median of the
per-pair ratios), the two matchings
must be bit-identical, and the consolidated /metrics scrape endpoint
must honor the prometheus-optional degradation contract (200 with
prometheus_client, clean 503 without; /metrics.json always 200).

With ``--fleet`` it runs the multi-tenant fleet gate (ISSUE 7): N
concurrent 512-scale trace-replayed sessions over a real localhost gRPC
seam (the fleet loadgen) must hold per-tenant assigned fraction >=
``fleet_min_assigned_frac``, per-tenant p99 warm-tick latency <=
``fleet_p99_tick_ms_max``, complete every tick for every tenant (no
starvation), and keep the per-session Jain fairness index >=
``fleet_fairness_floor`` — so an admission/fairness/backpressure
regression (or a sharded-fabric lock bug serializing tenants) cannot
merge on green unit tests alone.

With ``--quality`` it runs the decision-quality gate (ISSUE 8): the
golden trace replayed with the quality plane on must stay bit-for-bit
identical at threads {1, 2, 4}, the certified duality gap must hold
<= ``quality_gap_per_task_max`` (2x engine eps), every unassigned task
must carry a cause code, plan churn at 1% population churn must stay
<= ``quality_churn_ratio_max``, and the instrumented replay must stay
within the obs overhead budget — so a cert/taxonomy/stability
regression cannot merge on green unit tests alone.

With ``--chaos`` it runs the seeded fault-schedule gate (ISSUE 9): the
committed golden trace driven through a live loopback servicer under
the chaos plane — servicer kill + restart mid-run (warm checkpoint
rehydration), 5% RPC drop + 5% delay, duplicated deltas, one forced
shard blackout — must RECONVERGE WARM: zero full-snapshot reopens,
no tick lost or double-applied (the idempotent-retransmit dedup is
exercised and must fire), and every tick's plan bit-identical to the
fault-free replay. A second phase forces an eviction and asserts the
fallback ladder's counted reopen; phase C is the ZOMBIE-RESUME drill
(ISSUE 14): one of 3 real servicer processes is SIGSTOPped mid-run,
the failure detector must eject it autonomously (suspect->dead, zero
driver-owned kill events), its journals re-route along the ring, and
the resumed zombie must be fence-refused — zero double-applied ticks
(plans bit-identical to the fault-free replay), zero reopens, zero
false-positive ejections, time-to-detect under the committed floor;
phase D arms the per-tick solve deadline and asserts degraded
(stale-plan) answers are explicitly flagged, counted in obs, and
bounded by ``max_stale_ticks``. A recovery/degradation/autonomy
regression cannot merge on green unit tests alone.

With ``--dfleet`` it runs the distributed-fleet gate (ISSUE 12): the
loadgen drives sessions across THREE real servicer processes behind
the consistent-hash endpoint ring over a shared journal root, under
seeded drop/delay faults, and one process is SIGKILLed mid-run (its
orphaned journals re-routed along the ring). Every session must resume
WARM on a surviving process — zero full-snapshot reopens — with
per-tenant assigned fraction >= ``dfleet_min_assigned_frac``, session
fairness >= ``dfleet_fairness_floor``, staleness counted and <=
``dfleet_max_stale_total``, and zero lock-witness violations in the
surviving processes. A second phase live-migrates a process's sessions
(Migrate RPC + "moved:" redirects) before a graceful drain and holds
the same bars — so a routing/migration/handoff regression cannot merge
on green unit tests alone.

Usage: python scripts/perf_gate.py [--update-floor] [--wire] [--sinkhorn]
[--trace] [--obs] [--fleet] [--quality] [--chaos] [--dfleet]
(--update-floor rewrites perf_floor.json to 25% of this machine's
measured rate — run on the slowest supported host class, then commit.)
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

FLOOR_PATH = os.path.join(os.path.dirname(__file__), "perf_floor.json")
N = 2048


def wire_gate() -> int:
    """Loopback wire-path floor: v2 delta sessions vs v1 full snapshots
    at 16k x 16k with 1% churn (the ISSUE 2 acceptance bar)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import bench

    with open(FLOOR_PATH) as fh:
        floors = json.load(fh)
    res = bench.run_wire_bench(P=16384, T=16384, churn=0.01,
                               ticks=4, warmup=3)
    failures = []
    speedup_floor = floors["wire_v2_vs_v1_speedup_floor"]
    bytes_floor = floors["wire_v2_bytes_ratio_floor"]
    assigned_floor = floors["wire_v2_min_assigned_frac"]
    print(f"wire gate: v2 speedup {res['v2_speedup']}x "
          f"(floor {speedup_floor}x), bytes ratio {res['v2_bytes_ratio']}x "
          f"(floor {bytes_floor}x)")
    if res["v2_speedup"] < speedup_floor:
        failures.append(
            f"v2 delta tick only {res['v2_speedup']}x faster than v1 "
            f"full snapshot (floor {speedup_floor}x)"
        )
    if res["v2_bytes_ratio"] < bytes_floor:
        failures.append(
            f"v2 per-tick wire bytes only {res['v2_bytes_ratio']}x "
            f"smaller than v1 (floor {bytes_floor}x)"
        )
    for mode in ("v1", "v2"):
        frac = min(res["modes"][mode]["tick_assigned"]) / res["T"]
        print(f"wire gate: {mode} min assigned frac {frac:.3f}")
        if frac < assigned_floor:
            failures.append(
                f"{mode} steady-state assigned fraction {frac:.3f} below "
                f"{assigned_floor} — the wire win must not be bought with "
                "matching quality"
            )
    if failures:
        for f in failures:
            print(f"PERF GATE FAIL: {f}", file=sys.stderr)
        return 1
    print("wire perf gate OK")
    return 0


def sinkhorn_gate() -> int:
    """Sparse-Sinkhorn engine smoke (the ISSUE 3 acceptance bar): 4k x 4k
    thread-invariance + referee quality vs the plain auction on shared
    candidates, and the 16k x 16k warm-potential-carry speedup floor."""
    import dataclasses
    import time as _time

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    import bench
    from protocol_tpu import native
    from protocol_tpu.native.arena import NativeSolveArena
    from protocol_tpu.ops.cost import CostWeights

    with open(FLOOR_PATH) as fh:
        floors = json.load(fh)
    failures = []
    w = CostWeights()
    rng = np.random.default_rng(0)
    n = 4096
    ep = bench.synth_providers(rng, n)
    er = bench.synth_requirements(rng, n)
    cand_p, cand_c = native.fused_topk_candidates(
        ep, er, w, k=64, reverse_r=8, extra=16, threads=0
    )

    # ---- thread invariance (the -mt determinism contract)
    f1, g1, it1, _ = native.sinkhorn_sparse_mt(
        cand_p, cand_c, n, eps=0.1, max_iters=30, tol=1e-3, threads=1
    )
    f2, g2, it2, _ = native.sinkhorn_sparse_mt(
        cand_p, cand_c, n, eps=0.1, max_iters=30, tol=1e-3, threads=2
    )
    invariant = (
        np.array_equal(f1, f2) and np.array_equal(g1, g2) and it1 == it2
    )
    print(f"sinkhorn gate: thread-invariant {invariant} ({it1} iters)")
    if not invariant:
        failures.append(
            "sinkhorn-mt potentials differ between threads=1 and threads=2"
        )

    # ---- quality: anneal + referee vs the plain auction, same candidates
    phase_stats: list = []
    t0 = _time.perf_counter()
    f, _g = native.sinkhorn_sparse_anneal(
        cand_p, cand_c, n, eps_start=1.0, eps_end=0.05,
        iters_per_phase=50, tol=1e-2, threads=0, phase_stats=phase_stats,
    )
    t_pot = _time.perf_counter() - t0
    price0 = native.sinkhorn_referee_prices(f, cand_p, cand_c)
    p4t_s, _, _ = native.auction_sparse_mt(
        cand_p, cand_c, num_providers=n, eps_start=0.32, eps_end=0.02,
        threads=0, price=price0,
    )
    p4t_a, _, _ = native.auction_sparse_mt(
        cand_p, cand_c, num_providers=n, threads=0
    )

    def mean_cost(p4t):
        m = (cand_p == p4t[:, None]) & (p4t[:, None] >= 0)
        has = m.any(axis=1)
        j = m.argmax(axis=1)
        return float(cand_c[np.arange(n), j][has].mean())

    n_s, n_a = int((p4t_s >= 0).sum()), int((p4t_a >= 0).sum())
    c_s, c_a = mean_cost(p4t_s), mean_cost(p4t_a)
    pos = p4t_s[p4t_s >= 0]
    print(
        f"sinkhorn gate: rounding {n_s}/{n} vs auction {n_a}/{n}, "
        f"mean cost {c_s:.4f} vs {c_a:.4f} "
        f"({t_pot:.1f}s potentials, {sum(s['iters'] for s in phase_stats)} "
        "iters)"
    )
    if np.unique(pos).size != pos.size:
        failures.append("sinkhorn-mt rounding is not injective")
    if n_s < floors["sinkhorn_mt_min_assigned_vs_auction"] * n_a:
        failures.append(
            f"sinkhorn-mt rounding assigned {n_s} < "
            f"{floors['sinkhorn_mt_min_assigned_vs_auction']:.2f}x of "
            f"auction {n_a}"
        )
    if c_s > c_a * floors["sinkhorn_mt_cost_ratio_max"] + 1e-6:
        failures.append(
            f"sinkhorn-mt mean cost {c_s:.4f} exceeds "
            f"{floors['sinkhorn_mt_cost_ratio_max']:.2f}x of auction "
            f"{c_a:.4f}"
        )

    # ---- warm-potential carry: 1% churn warm re-solve >= 2x over cold
    # at 16k x 16k (the arena's candidate + dual carry, end to end)
    n16 = 16384
    ep16 = bench.synth_providers(np.random.default_rng(2), n16)
    er16 = bench.synth_requirements(np.random.default_rng(3), n16)
    arena = NativeSolveArena(engine="sinkhorn", threads=0)
    t0 = _time.perf_counter()
    arena.solve(ep16, er16, w)
    t_cold = _time.perf_counter() - t0
    churn_rng = np.random.default_rng(4)
    rows = churn_rng.choice(n16, n16 // 100, replace=False)
    price = np.array(ep16.price, copy=True)
    price[rows] = churn_rng.uniform(0.5, 4.0, rows.size).astype(np.float32)
    ep16b = dataclasses.replace(ep16, price=price)
    t0 = _time.perf_counter()
    p4t_w = arena.solve(ep16b, er16, w)
    t_warm = _time.perf_counter() - t0
    speedup = t_cold / max(t_warm, 1e-9)
    frac = int((p4t_w >= 0).sum()) / n16
    print(
        f"sinkhorn gate: 16k warm {t_warm:.2f}s vs cold {t_cold:.2f}s "
        f"({speedup:.1f}x, floor "
        f"{floors['sinkhorn_mt_warm_speedup_floor']}x); warm assigned "
        f"frac {frac:.3f}"
    )
    if speedup < floors["sinkhorn_mt_warm_speedup_floor"]:
        failures.append(
            f"sinkhorn warm re-solve only {speedup:.2f}x faster than cold "
            f"(floor {floors['sinkhorn_mt_warm_speedup_floor']}x)"
        )
    if frac < floors["sinkhorn_mt_min_assigned_frac"]:
        failures.append(
            f"sinkhorn warm assigned fraction {frac:.3f} below "
            f"{floors['sinkhorn_mt_min_assigned_frac']}"
        )

    if failures:
        for fmsg in failures:
            print(f"PERF GATE FAIL: {fmsg}", file=sys.stderr)
        return 1
    print("sinkhorn perf gate OK")
    return 0


def cand_gate() -> int:
    """Incremental-candidate-maintenance gate (ISSUE 13): a 16k x 16k
    1%-churn warm tick must repair the persistent structure with ZERO
    full-matrix candidate passes, beat the arena's own cold generation
    by >= ``gen_warm_speedup_floor`` (measured at threads=2 — the ratio
    is Amdahl-sensitive at high core counts, where the cold pass keeps
    scaling while the repair wall is already tens of ms), touch at most
    ``cand_repair_cells_frac_max`` of the P*T cell plane (the
    machine-independent work bound), and leave the structure
    BIT-IDENTICAL to a from-scratch rebuild. The bucketed cold pruner is
    held to its own exactness bar on the same population."""
    import dataclasses
    import time as _time

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    import bench
    from protocol_tpu import native
    from protocol_tpu.native.arena import NativeSolveArena
    from protocol_tpu.ops.cost import CostWeights

    with open(FLOOR_PATH) as fh:
        floors = json.load(fh)
    failures = []
    w = CostWeights()
    n = 16384
    # measurement basis: bench.synth_providers(rng(2)) x
    # bench.synth_requirements(rng(3)) — the same population every floor
    # in perf_floor.json's cand_* family was measured against. The ISA
    # the run dispatched to is part of the basis too (runtime-selected
    # per host/env), so record both in the gate output.
    ep = bench.synth_providers(np.random.default_rng(2), n)
    er = bench.synth_requirements(np.random.default_rng(3), n)
    native.load()
    print(
        f"cand gate: population bench.synth_providers(rng(2)) x "
        f"bench.synth_requirements(rng(3)), n={n}, "
        f"native_isa={native.current_isa()}"
    )

    # ---- bucketed cold pruner: bit-identical to the full scan, and it
    # genuinely prunes on this (GPU-selective) population. The
    # reference is the v2 full scan (rev_out requested) — both paths
    # dispatch through the same runtime ISA table (scalar/avx2/avx512),
    # so within one process the float pipeline is pinned and the
    # bucketed pruner must reproduce the full scan bit-for-bit
    st: dict = {}
    cand_b = native.fused_topk_candidates(
        ep, er, w, k=64, threads=2, bucketed=True, stats=st
    )
    cand_f = native.fused_topk_candidates(
        ep, er, w, k=64, threads=2,
        rev_out=np.zeros((n, 8), np.uint64),
    )
    if not (
        np.array_equal(cand_b[0], cand_f[0])
        and np.array_equal(cand_b[1], cand_f[1])
    ):
        failures.append("bucketed cold generation is not bit-identical")
    visit_frac = st["gen_visited"] / float(n * n)
    print(
        f"cand gate: bucketed cold visited {visit_frac:.2%} of P*T "
        f"({st['gen_fallback_rows']} fallback rows), bit-identical "
        f"{not failures}"
    )

    # ---- warm repair floor: cold arena solve, 1% price churn, one warm
    # tick — zero full-matrix passes, >= floor gen speedup, structure
    # bit-identical to a from-scratch rebuild
    arena = NativeSolveArena(threads=2)
    arena.solve(ep, er, w)
    if arena.last_stats["cand_cold_passes"] != 1:
        failures.append(
            f"cold solve reported cand_cold_passes="
            f"{arena.last_stats['cand_cold_passes']}, want 1"
        )
    gen_cold = float(arena.last_stats["gen_ms"])
    churn_rng = np.random.default_rng(4)
    rows = churn_rng.choice(n, n // 100, replace=False)
    price = np.array(ep.price, copy=True)
    price[rows] = churn_rng.uniform(0.5, 4.0, rows.size).astype(np.float32)
    ep2 = dataclasses.replace(ep, price=price)
    t0 = _time.perf_counter()
    p4t = arena.solve(ep2, er, w)
    t_warm = _time.perf_counter() - t0
    stats = arena.last_stats
    gen_warm = float(stats["gen_ms"])
    speedup = gen_cold / max(gen_warm, 1e-9)
    frac = int((p4t >= 0).sum()) / n
    print(
        f"cand gate: 16k 1%-churn warm gen {gen_warm:.1f}ms vs cold "
        f"{gen_cold:.1f}ms ({speedup:.1f}x, floor "
        f"{floors['gen_warm_speedup_floor']}x); tick {t_warm:.2f}s, "
        f"assigned frac {frac:.3f}, cand_cold_passes "
        f"{stats['cand_cold_passes']}"
    )
    if stats["cand_cold_passes"] != 0:
        failures.append(
            f"warm 1%-churn tick ran {stats['cand_cold_passes']} "
            "full-matrix candidate passes (want 0)"
        )
    if speedup < floors["gen_warm_speedup_floor"]:
        failures.append(
            f"warm candidate repair only {speedup:.1f}x faster than cold "
            f"generation (floor {floors['gen_warm_speedup_floor']}x)"
        )
    if frac < floors["cand_min_assigned_frac"]:
        failures.append(
            f"warm assigned fraction {frac:.3f} below "
            f"{floors['cand_min_assigned_frac']}"
        )

    # ---- machine-independent work bound: cells the repair scored
    # (requires the obs plane for eng_ stats; re-run the repair kernel
    # directly so the gate never depends on the obs toggle)
    rev = np.zeros((n, 8), np.uint64)
    slack = (np.zeros((n, 16), np.int32), np.zeros((n, 16), np.float32))
    cp, cc = native.fused_topk_candidates(
        ep, er, w, k=64, threads=2, bucketed=True, rev_out=rev,
        slack_out=slack,
    )
    rst: dict = {}
    native.repair_topk_candidates(
        ep2, er, w, cp, cc, rev, rows.astype(np.int32),
        np.zeros(0, np.int32), k=64, threads=2, slack=slack, stats=rst,
    )
    cells_frac = rst["cand_repair_exact_scores"] / float(n * n)
    print(
        f"cand gate: repair scored {cells_frac:.2%} of P*T "
        f"(ceiling {floors['cand_repair_cells_frac_max']:.0%}), "
        f"{rst['cand_repair_rescans']} row rescans, "
        f"{rst['cand_repair_rows']} merges"
    )
    if cells_frac > floors["cand_repair_cells_frac_max"]:
        failures.append(
            f"repair scored {cells_frac:.2%} of the cell plane "
            f"(ceiling {floors['cand_repair_cells_frac_max']:.0%})"
        )

    # ---- repaired-structure exactness on the gate population
    rev_ref = np.zeros((n, 8), np.uint64)
    ref_p, ref_c = native.fused_topk_candidates(
        ep2, er, w, k=64, threads=2, rev_out=rev_ref
    )
    if not (
        np.array_equal(cp, ref_p) and np.array_equal(cc, ref_c)
        and np.array_equal(rev, rev_ref)
        and np.array_equal(arena._cand_p, ref_p)
        and np.array_equal(arena._cand_c, ref_c)
    ):
        failures.append(
            "repaired candidate structure is not bit-identical to a "
            "from-scratch rebuild"
        )
    else:
        print("cand gate: repaired structure bit-identical to rebuild")

    if failures:
        for fmsg in failures:
            print(f"PERF GATE FAIL: {fmsg}", file=sys.stderr)
        return 1
    print("cand perf gate OK")
    return 0


def simd_gate() -> int:
    """Runtime-ISA dispatch gate (ISSUE 16): on AVX2-capable hosts the
    vector scoring path must beat the scalar referee by >=
    ``simd_cold_speedup_floor`` on the 16k bucketed cold candidate
    generation at threads=1 (pure kernel throughput, no Amdahl mixing),
    every ISA's plan must be bit-identical across threads {1, 2, 4},
    the two vector ISAs — which share one fmaf-matched float pipeline —
    must be bit-identical to EACH OTHER, and the widest vector plan must
    match the scalar referee row-for-row up to the documented
    float-pipeline tolerance (``simd_referee_cost_tol_abs`` on
    provider-agreeing rows; near-tie provider reorders capped at
    ``simd_referee_row_mismatch_frac_max`` of rows). The warm repair
    sweep speedup is measured and RECORDED (printed, not floored — at
    1% churn the sweep wall is tens of ms and host-jitter dominated).
    On hosts without AVX2 the vector floors are not applicable and the
    gate passes with an explicit SKIP line."""
    import dataclasses
    import time as _time

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    import bench
    from protocol_tpu import native
    from protocol_tpu.ops.cost import CostWeights

    with open(FLOOR_PATH) as fh:
        floors = json.load(fh)
    failures: list = []

    native.load()
    if not native.isa_supported("avx2"):
        print(
            "simd gate: host CPU lacks AVX2 — scalar-only dispatch, "
            "vector floors not applicable (SKIP, pass)"
        )
        return 0

    w = CostWeights()
    n = 16384
    # same measurement basis as the cand gate: every simd_* floor in
    # perf_floor.json was measured against bench.synth_providers(rng(2))
    # x bench.synth_requirements(rng(3)) at n=16384
    ep = bench.synth_providers(np.random.default_rng(2), n)
    er = bench.synth_requirements(np.random.default_rng(3), n)
    isas = ["scalar", "avx2"]
    if native.isa_supported("avx512"):
        isas.append("avx512")
    print(
        f"simd gate: population bench.synth_providers(rng(2)) x "
        f"bench.synth_requirements(rng(3)), n={n}, isas={isas}"
    )

    prev_env = os.environ.get("PROTOCOL_TPU_NATIVE_ISA")
    prev_isa = native.current_isa()

    def cold(threads: int) -> tuple:
        return native.fused_topk_candidates(
            ep, er, w, k=64, threads=threads, bucketed=True
        )

    try:
        gen_s: dict = {}
        plans: dict = {}
        rep_s: dict = {}
        churn_rng = np.random.default_rng(4)
        rows = churn_rng.choice(n, n // 100, replace=False).astype(np.int32)
        price = np.array(ep.price, copy=True)
        price[rows] = churn_rng.uniform(0.5, 4.0, rows.size).astype(
            np.float32
        )
        ep2 = dataclasses.replace(ep, price=price)

        for isa in isas:
            eff = native.set_isa(isa)
            if eff != isa:
                failures.append(
                    f"set_isa({isa!r}) clamped to {eff!r} on a host that "
                    f"reports isa_supported({isa!r})"
                )
                continue
            cold(1)  # warm run: page in the population before timing
            best = float("inf")
            for _ in range(2):
                t0 = _time.perf_counter()
                plan = cold(1)
                best = min(best, _time.perf_counter() - t0)
            gen_s[isa] = best
            plans[isa] = plan

            # within-ISA determinism: threads {1, 2, 4} bit-identical
            for th in (2, 4):
                pth = cold(th)
                if not (
                    np.array_equal(plan[0], pth[0])
                    and np.array_equal(plan[1], pth[1])
                ):
                    failures.append(
                        f"{isa}: bucketed cold plan differs between "
                        f"threads=1 and threads={th}"
                    )

            # warm repair sweep (the transposed-pass kernel): build the
            # persistent structure once, churn 1% of providers, time the
            # in-place repair (fresh copies per rep — repair mutates)
            rev = np.zeros((n, 8), np.uint64)
            slack = (
                np.zeros((n, 16), np.int32),
                np.zeros((n, 16), np.float32),
            )
            cp, cc = native.fused_topk_candidates(
                ep, er, w, k=64, threads=1, bucketed=True, rev_out=rev,
                slack_out=slack,
            )
            best_r = float("inf")
            for _ in range(3):
                cp_i = np.array(cp, copy=True)
                cc_i = np.array(cc, copy=True)
                rev_i = np.array(rev, copy=True)
                slack_i = (
                    np.array(slack[0], copy=True),
                    np.array(slack[1], copy=True),
                )
                t0 = _time.perf_counter()
                native.repair_topk_candidates(
                    ep2, er, w, cp_i, cc_i, rev_i, rows,
                    np.zeros(0, np.int32), k=64, threads=1,
                    slack=slack_i, stats={},
                )
                best_r = min(best_r, _time.perf_counter() - t0)
            rep_s[isa] = best_r

        # ---- throughput floor: widest vector ISA vs the scalar referee
        if "scalar" in gen_s and "avx2" in gen_s:
            v = "avx512" if "avx512" in gen_s else "avx2"
            cold_speedup = gen_s["scalar"] / max(gen_s[v], 1e-9)
            rep_speedup = rep_s["scalar"] / max(rep_s[v], 1e-9)
            print(
                f"simd gate: 16k bucketed cold gen scalar "
                f"{gen_s['scalar'] * 1e3:.0f}ms vs {v} "
                f"{gen_s[v] * 1e3:.0f}ms ({cold_speedup:.2f}x, floor "
                f"{floors['simd_cold_speedup_floor']}x); warm repair "
                f"sweep scalar {rep_s['scalar'] * 1e3:.1f}ms vs {v} "
                f"{rep_s[v] * 1e3:.1f}ms ({rep_speedup:.2f}x, recorded)"
            )
            if cold_speedup < floors["simd_cold_speedup_floor"]:
                failures.append(
                    f"{v} cold generation only {cold_speedup:.2f}x "
                    f"scalar (floor {floors['simd_cold_speedup_floor']}x)"
                )

        # ---- cross-vector identity: avx2 and avx512 share one
        # fmaf-matched pipeline, so their plans must be EXACTLY equal
        if "avx2" in plans and "avx512" in plans:
            if not (
                np.array_equal(plans["avx2"][0], plans["avx512"][0])
                and np.array_equal(plans["avx2"][1], plans["avx512"][1])
            ):
                failures.append(
                    "avx2 and avx512 bucketed cold plans are not "
                    "bit-identical (shared-pipeline contract)"
                )
            else:
                print("simd gate: avx2 == avx512 plans bit-identical")

        # ---- scalar-referee equivalence with the documented tolerance
        if "scalar" in plans and "avx2" in plans:
            v = "avx512" if "avx512" in plans else "avx2"
            sp, sc = plans["scalar"]
            vp, vc = plans[v]
            same = np.all(sp == vp, axis=1)
            mism_frac = float(1.0 - same.mean())
            max_dc = (
                float(np.abs(sc[same] - vc[same]).max())
                if bool(same.any()) else 0.0
            )
            print(
                f"simd gate: scalar referee vs {v}: {mism_frac:.4%} rows "
                f"with provider reorders (cap "
                f"{floors['simd_referee_row_mismatch_frac_max']:.2%}), "
                f"max |cost delta| {max_dc:.2e} on agreeing rows (tol "
                f"{floors['simd_referee_cost_tol_abs']:.0e})"
            )
            if mism_frac > floors["simd_referee_row_mismatch_frac_max"]:
                failures.append(
                    f"scalar-vs-{v} provider mismatch on {mism_frac:.4%} "
                    f"of rows (cap "
                    f"{floors['simd_referee_row_mismatch_frac_max']:.2%})"
                )
            if max_dc > floors["simd_referee_cost_tol_abs"]:
                failures.append(
                    f"scalar-vs-{v} cost delta {max_dc:.2e} exceeds "
                    f"documented tolerance "
                    f"{floors['simd_referee_cost_tol_abs']:.0e}"
                )
    finally:
        if prev_env is None:
            os.environ.pop("PROTOCOL_TPU_NATIVE_ISA", None)
        else:
            os.environ["PROTOCOL_TPU_NATIVE_ISA"] = prev_env
        native._apply_isa(native.load(), prev_isa)

    if failures:
        for fmsg in failures:
            print(f"PERF GATE FAIL: {fmsg}", file=sys.stderr)
        return 1
    print("simd perf gate OK")
    return 0


def stream_gate() -> int:
    """Event-driven streaming gate (ISSUE 15). Three phases:

    A — golden stream trace (artifacts/golden_stream_512x512.trace)
        replayed event-by-event at threads {1, 2, 4}: every event's
        plan bit-identical to the recording, ZERO full-matrix candidate
        passes, and every reconciliation plan bit-identical to the
        batch-shadow oracle (a fresh always-cold arena solving the
        accumulated columns at the same boundaries). A ceiling-armed
        replay asserts the certified-gap contract: every SERVED answer
        within ``stream_gap_ceiling`` or a fresh inline reconcile.
    B — the same trace under seeded drop/dup/reorder event chaos: the
        dedup ladder must fire (duplicates/overtaken events acked, not
        applied) and the FINAL reconciled plan must be bit-identical to
        the fault-free replay's (convergence by construction).
    C — 16k x 16k with 1% churn delivered as SINGLE heartbeat events:
        p99 per-event apply+repair latency must beat the full warm
        batch tick on the same host by ``stream_event_speedup_floor``
        (floor committed conservatively below measured, per this file's
        convention) and stay under ``stream_event_p99_ms_max``; zero
        full-matrix passes between reconciles; the closing
        reconciliation must restore >= ``stream_min_assigned_frac``."""
    import dataclasses
    import time as _time

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    import bench
    from protocol_tpu.faults.plan import ChaosConfig
    from protocol_tpu.native.arena import NativeSolveArena
    from protocol_tpu.ops.cost import CostWeights
    from protocol_tpu.proto import wire
    from protocol_tpu.stream.engine import StreamEngine
    from protocol_tpu.stream.events import StreamEvent
    from protocol_tpu.stream.replay import (
        batch_shadow_replay,
        stream_replay,
    )
    from protocol_tpu.trace import format as tfmt

    with open(FLOOR_PATH) as fh:
        floors = json.load(fh)
    failures = []
    golden = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "artifacts", "golden_stream_512x512.trace",
    )

    # ---- phase A: replay identity + reconcile bit-identity + ceiling
    base = None
    for th in (1, 2, 4):
        rep = stream_replay(golden, threads=th, keep_recon_p4ts=True)
        if rep["divergence"] is not None:
            failures.append(
                f"stream replay diverged at threads={th}: "
                f"{rep['divergence']}"
            )
            continue
        if rep["cand_cold_passes"] != 0:
            failures.append(
                f"stream replay ran {rep['cand_cold_passes']} "
                f"full-matrix candidate passes at threads={th} (want 0)"
            )
        shadow = batch_shadow_replay(
            golden, rep["recon_ticks"], threads=th
        )
        pairs = list(zip(rep["recon_p4ts"], shadow["p4ts"]))
        bad = [
            i for i, (a, b) in enumerate(pairs)
            if not np.array_equal(a, b)
        ]
        if bad or len(pairs) != len(rep["recon_ticks"]):
            failures.append(
                f"reconciliation not bit-identical to the batch shadow "
                f"at threads={th} (windows {bad})"
            )
        if th == 1:
            base = rep
        print(
            f"stream gate A: threads={th} events={rep['events']} "
            f"reconciles={rep['reconciles']} bit-identical, shadow OK"
        )
    ceiling = floors["stream_gap_ceiling"]
    ceil_rep = stream_replay(golden, gap_ceiling=ceiling, verify=False)
    if ceil_rep["gap_served_max"] > ceiling + 1e-9:
        failures.append(
            f"ceiling-armed replay served gap "
            f"{ceil_rep['gap_served_max']:.4f} above the "
            f"{ceiling} ceiling (breach must reconcile inline)"
        )
    print(
        f"stream gate A: ceiling {ceiling} armed -> "
        f"{ceil_rep['reconciles']} reconciles, served gap max "
        f"{ceil_rep['gap_served_max']:.4f}"
    )

    # ---- phase B: chaos'd event stream converges via the dedup ladder
    chaos = ChaosConfig.from_spec("seed=5,drop=0.08,dup=0.08,reorder=0.08")
    ch = stream_replay(
        golden, chaos=chaos, verify=False, keep_recon_p4ts=True
    )
    if ch["deduped"] <= 0:
        failures.append(
            "chaos'd stream never hit the dedup ladder (dup/reorder "
            "events must be acked without applying)"
        )
    if base is not None and not np.array_equal(
        base["recon_p4ts"][-1], ch["recon_p4ts"][-1]
    ):
        failures.append(
            "chaos'd event stream did NOT converge: final reconciled "
            "plan differs from the fault-free replay"
        )
    print(
        f"stream gate B: chaos drop/dup/reorder -> "
        f"{ch['deduped']} deduped of {ch['events']} deliveries, final "
        f"reconcile bit-identical {base is not None}"
    )

    # ---- phase C: 16k, 1% churn as single events vs the batch tick
    w = CostWeights()
    n = 16384
    ep = bench.synth_providers(np.random.default_rng(2), n)
    er = bench.synth_requirements(np.random.default_rng(3), n)

    batch = NativeSolveArena(threads=1)
    batch.solve(ep, er, w)
    rng = np.random.default_rng(4)
    cur = ep
    batch_walls = []
    for _ in range(3):
        rows = rng.choice(n, n // 100, replace=False)
        price = np.array(cur.price, copy=True)
        load = np.array(cur.load, copy=True)
        price[rows] = rng.uniform(0.5, 4.0, rows.size).astype(np.float32)
        load[rows] = rng.uniform(0, 1, rows.size).astype(np.float32)
        cur = dataclasses.replace(cur, price=price, load=load)
        t0 = _time.perf_counter()
        batch.solve(cur, er, w)
        batch_walls.append((_time.perf_counter() - t0) * 1e3)
    batch_ms = float(np.median(batch_walls))

    # tight per-event bid budget: a saturated-pocket give-up war
    # amortizes across events instead of landing on one event's p99
    # (the unbudgeted war is exactly what the batch tick pays)
    arena = NativeSolveArena(threads=1, event_max_bids=4096)
    arena.solve(ep, er, w)
    se = StreamEngine(arena, w, reconcile_every=10 ** 9)
    p_cols = wire.canon_columns(ep, tfmt.P_TRACE_DTYPES)
    rng = np.random.default_rng(4)
    walls = []
    cold_passes = 0
    seqs: dict = {}
    for _ in range(3):
        rows = rng.choice(n, n // 100, replace=False)
        newp = rng.uniform(0.5, 4.0, rows.size).astype(np.float32)
        newl = rng.uniform(0, 1, rows.size).astype(np.float32)
        p_cols["price"] = p_cols["price"].copy()
        p_cols["load"] = p_cols["load"].copy()
        p_cols["price"][rows] = newp
        p_cols["load"][rows] = newl
        for r in rows.tolist():
            rr = np.asarray([r], np.int32)
            seqs[r] = seqs.get(r, -1) + 1
            ev = StreamEvent(
                kind="heartbeat", source=f"p{r}", seq=seqs[r],
                provider_rows=rr,
                p_cols={nm: a[rr] for nm, a in p_cols.items()},
                task_rows=np.zeros(0, np.int32), r_cols={},
            )
            t0 = _time.perf_counter()
            res = se.apply(ev)
            walls.append((_time.perf_counter() - t0) * 1e3)
            cold_passes += int(res.stats.get("cand_cold_passes", 0))
    walls_a = np.asarray(walls)
    p50 = float(np.percentile(walls_a, 50))
    p99 = float(np.percentile(walls_a, 99))
    recon = se.reconcile()
    frac = int((recon.plan >= 0).sum()) / n
    ratio = batch_ms / max(p99, 1e-9)
    print(
        f"stream gate C: {walls_a.size} single events at 16k — p50 "
        f"{p50:.2f}ms p99 {p99:.2f}ms vs warm batch tick "
        f"{batch_ms:.0f}ms ({ratio:.1f}x, floor "
        f"{floors['stream_event_speedup_floor']}x); cold passes "
        f"{cold_passes}, post-reconcile assigned {frac:.4f}"
    )
    if cold_passes != 0:
        failures.append(
            f"{cold_passes} full-matrix candidate passes between "
            "reconciles (want 0)"
        )
    if ratio < floors["stream_event_speedup_floor"]:
        failures.append(
            f"per-event p99 only {ratio:.1f}x below the warm batch "
            f"tick (floor {floors['stream_event_speedup_floor']}x)"
        )
    if p99 > floors["stream_event_p99_ms_max"]:
        failures.append(
            f"per-event p99 {p99:.2f}ms above the "
            f"{floors['stream_event_p99_ms_max']}ms ceiling"
        )
    if frac < floors["stream_min_assigned_frac"]:
        failures.append(
            f"post-reconcile assigned fraction {frac:.4f} below "
            f"{floors['stream_min_assigned_frac']}"
        )

    if failures:
        for fmsg in failures:
            print(f"PERF GATE FAIL: {fmsg}", file=sys.stderr)
        return 1
    print("stream perf gate OK")
    return 0


def paired_overhead(run, pairs: int = 9):
    """Robust A/B overhead estimate for a noisy wall: ``run(flag)``
    returns the chain wall with instrumentation on (True) / off
    (False). Runs ``pairs`` adjacent on/off pairs in ALTERNATING order
    (a fixed order hands one flag the other's warmed allocator/cache
    state every round, which reads as a systematic few-percent
    "overhead" that is not the plane's) and takes the MEDIAN of the
    per-pair ratios: the two runs of a pair sit next to each other in
    time, so host-noise regimes (cold-solve walls on this 2-core
    container swing 490-660 ms) hit both sides of a ratio alike, and
    the median shrugs off the pairs a background burst still split —
    where min-of-N needs the two independent minima to land in the
    same regime, which 5-6 samples of 25%-jitter walls routinely
    don't. Returns (median on_s, median off_s, overhead fraction).
    """
    ons, offs, ratios = [], [], []
    for i in range(pairs):
        order = (True, False) if i % 2 == 0 else (False, True)
        pair = {}
        for flag in order:
            pair[flag] = run(flag)
        ons.append(pair[True])
        offs.append(pair[False])
        ratios.append(pair[True] / pair[False])
    ratios.sort()
    med = ratios[len(ratios) // 2]
    ons.sort()
    offs.sort()
    return ons[len(ons) // 2], offs[len(offs) // 2], med - 1.0


def overhead_within(run, max_frac: float, label: str,
                    attempts: int = 3) -> bool:
    """True when some attempt's paired-overhead estimate lands within
    ``max_frac``. One attempt's estimator noise on this host class is
    +/- a few percent — the same order as the budget — so a single
    unlucky draw must not fail the build; a REAL regression (the plane
    suddenly costing 2x the budget) sits outside the noise band and
    fails every attempt. Prints each attempt."""
    for attempt in range(attempts):
        on, off, overhead = paired_overhead(run)
        print(
            f"{label}: instrumented {on * 1e3:.1f} ms vs "
            f"{off * 1e3:.1f} ms (median-of-9 paired, attempt "
            f"{attempt + 1}/{attempts}) — overhead {overhead:+.2%} "
            f"(max {max_frac:.0%})"
        )
        if overhead <= max_frac:
            return True
    return False


def arena_chain_overhead(label: str, max_frac: float):
    """THE instrumentation-overhead experiment the --obs and --quality
    gates share: a 4k arena chain (cold + 1%-churn warm tick +
    byte-identical short-circuit) timed instrumented vs uninstrumented
    (paired alternating runs, median of per-pair ratios). The quality
    plane rides ``obs.enabled()``, so the instrumented chain exercises
    spans + native EngineStats + outcome/margin buffers + the
    certificate pass + tick_quality in one go. Returns ``(within,
    results)`` — ``results[flag]`` holds the chain's three matchings
    for the bit-identity check.

    Budget note (ISSUE 13): ``obs_overhead_max_frac`` was recalibrated
    0.03 -> 0.05 when incremental candidate maintenance shrank the
    chain's DENOMINATOR ~30% (bucketed cold gen + warm repair). The
    plane's absolute cost per solve is unchanged (~1.3 ms: the
    margin/certificate pass + tick_quality + buffers); 5% of the faster
    chain is the same milliseconds the original 3% bar licensed — a
    real instrumentation regression still fails every attempt."""
    import dataclasses

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    import bench
    from protocol_tpu import obs
    from protocol_tpu.native.arena import NativeSolveArena
    from protocol_tpu.ops.cost import CostWeights

    n = 4096
    rng = np.random.default_rng(0)
    ep = bench.synth_providers(rng, n)
    er = bench.synth_requirements(rng, n)
    w = CostWeights()
    churn_rng = np.random.default_rng(1)
    rows = churn_rng.choice(n, n // 100, replace=False)
    price = np.array(ep.price, copy=True)
    price[rows] = churn_rng.uniform(0.5, 4.0, rows.size).astype(np.float32)
    ep_b = dataclasses.replace(ep, price=price)

    def run(instrumented: bool):
        obs.set_enabled(instrumented)
        try:
            arena = NativeSolveArena(threads=0)
            t0 = time.perf_counter()
            p1 = arena.solve(ep, er, w)       # cold
            p2 = arena.solve(ep_b, er, w)     # 1% warm churn tick
            p3 = arena.solve(ep_b, er, w)     # byte-identical short-circuit
            return time.perf_counter() - t0, (p1, p2, p3)
        finally:
            obs.set_enabled(True)

    run(False)  # warm the native build/load + allocator
    results: dict = {}

    def timed(flag: bool) -> float:
        wall, res = run(flag)
        results.setdefault(flag, res)
        return wall

    # 5 attempts, not 3: measured single-attempt noise on a contended
    # 2-core host is +/-10% — the same order as 3x the budget — and the
    # true plane cost sits near zero, so unlucky triples false-failed
    # ~1 in 3 gate runs. A REAL regression (2x the budget, every run)
    # still fails all five.
    return overhead_within(timed, max_frac, label, attempts=5), results


def obs_gate() -> int:
    """Observability-plane gate (ISSUE 6): (a) overhead — an
    instrumented 4k arena chain (cold + warm + short-circuit tick, spans
    and native EngineStats on) must stay within
    ``obs_overhead_max_frac`` of the uninstrumented chain (paired
    alternating runs, median of per-pair ratios — host jitter cannot
    false-fail); (b) the instrumented and
    uninstrumented matchings must be BIT-IDENTICAL (observability must
    observe, never perturb); (c) the consolidated /metrics scrape
    endpoint must answer 200 with prometheus_client installed and a
    clean 503 without it (the degradation contract), with
    /metrics.json always 200."""
    import urllib.error
    import urllib.request

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    from protocol_tpu.obs.metrics import prometheus_available

    with open(FLOOR_PATH) as fh:
        floors = json.load(fh)
    failures = []
    max_frac = floors["obs_overhead_max_frac"]
    within, results = arena_chain_overhead("obs gate", max_frac)
    identical = all(
        np.array_equal(a, b)
        for a, b in zip(results[True], results[False])
    )
    print(f"obs gate: bit-identical {identical}")
    if not identical:
        failures.append(
            "instrumented matching differs from uninstrumented — "
            "observability must never perturb results"
        )
    if not within:
        failures.append(
            f"obs instrumentation overhead exceeds {max_frac:.0%} of "
            "the uninstrumented 4k solve chain on every attempt"
        )

    # ---- /metrics scrape smoke (degradation contract)
    from protocol_tpu.services.scheduler_grpc import serve

    server = serve("127.0.0.1:0", metrics_port=0)
    try:
        base = f"http://127.0.0.1:{server.metrics.port}"
        try:
            body = urllib.request.urlopen(base + "/metrics", timeout=10)
            code, text = body.status, body.read().decode()
        except urllib.error.HTTPError as e:
            code, text = e.code, e.read().decode()
        if prometheus_available():
            ok = code == 200 and "scheduler_obs" in text
            print(f"obs gate: /metrics {code} (prometheus present)")
            if not ok:
                failures.append(
                    f"/metrics answered {code} without the obs families "
                    "despite prometheus_client being installed"
                )
        else:
            print(f"obs gate: /metrics {code} (prometheus absent)")
            if code != 503:
                failures.append(
                    f"/metrics answered {code} without prometheus_client "
                    "— the degradation contract promises a clean 503"
                )
        jr = urllib.request.urlopen(base + "/metrics.json", timeout=10)
        jbody = jr.read().decode()
        if jr.status != 200 or "obs" not in jbody:
            failures.append(
                "/metrics.json must always serve the authoritative "
                f"snapshot (got {jr.status})"
            )
        else:
            print("obs gate: /metrics.json 200 (authoritative snapshot)")
    finally:
        if server.metrics is not None:
            server.metrics.stop()
        server.stop(grace=None)

    if failures:
        for fmsg in failures:
            print(f"PERF GATE FAIL: {fmsg}", file=sys.stderr)
        return 1
    print("obs perf gate OK")
    return 0


def fleet_gate() -> int:
    """Multi-tenant fleet gate (the ISSUE 7 acceptance bar): 8
    concurrent 512-scale sessions across 2 tenants and 2 shards on CPU
    must hold per-tenant quality/latency floors with nobody starved."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from protocol_tpu.fleet.loadgen import run_load

    with open(FLOOR_PATH) as fh:
        floors = json.load(fh)
    failures = []
    sessions, tenants, ticks = 8, 2, 6
    res = run_load(
        sessions=sessions, tenants=tenants, providers=512, tasks=512,
        ticks=ticks, churn=0.02, shards=2, kernel="native-mt:1",
        max_workers=8,
    )
    for e in res["errors"]:
        failures.append(f"session {e['session']} errored: {e['error']}")
    per_tenant_ticks = (sessions // tenants) * (ticks + 1)
    per_tenant_warm = (sessions // tenants) * ticks
    for t, a in res["tenants"].items():
        p99 = a["warm_tick"].get("p99_ms", 0.0)
        warm_count = a["warm_tick"].get("count", 0)
        print(
            f"fleet gate: {t} sessions={a['sessions']} "
            f"p50={a['warm_tick'].get('p50_ms')}ms p99={p99}ms "
            f"min-assigned={a['min_assigned_frac']} "
            f"ticks={a['ticks_done']}/{per_tenant_ticks} "
            f"warm={warm_count}/{per_tenant_warm} "
            f"refused={a['refused']} reopens={a['reopens']}"
        )
        if warm_count < per_tenant_warm:
            # reopen-served ticks are classified COLD, so an
            # eviction-thrash regression shows up as missing warm
            # ticks — and a {count: 0} histogram must never slide
            # past the p99 ceiling on its 0.0 default
            failures.append(
                f"tenant {t} recorded only {warm_count}/"
                f"{per_tenant_warm} warm delta ticks — deltas were "
                "refused or re-served via snapshot reopens"
            )
        if a["min_assigned_frac"] < floors["fleet_min_assigned_frac"]:
            failures.append(
                f"tenant {t} assigned fraction {a['min_assigned_frac']} "
                f"below {floors['fleet_min_assigned_frac']}"
            )
        if p99 > floors["fleet_p99_tick_ms_max"]:
            failures.append(
                f"tenant {t} p99 warm tick {p99}ms over "
                f"{floors['fleet_p99_tick_ms_max']}ms"
            )
        if a["ticks_done"] < per_tenant_ticks:
            failures.append(
                f"tenant {t} completed only {a['ticks_done']}/"
                f"{per_tenant_ticks} ticks — starved"
            )
    fairness = res["fairness_index_sessions"]
    print(
        f"fleet gate: session fairness (Jain) {fairness} "
        f"(floor {floors['fleet_fairness_floor']}), aggregate "
        f"{res['aggregate_warm_ticks_per_s']} warm ticks/s"
    )
    if fairness < floors["fleet_fairness_floor"]:
        failures.append(
            f"session fairness index {fairness} below "
            f"{floors['fleet_fairness_floor']}"
        )
    if not res["metrics_endpoint_ok"]:
        failures.append("/metrics.json endpoint did not answer")
    if failures:
        for fmsg in failures:
            print(f"PERF GATE FAIL: {fmsg}", file=sys.stderr)
        return 1
    print("fleet perf gate OK")
    return 0


GOLDEN_TRACE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "artifacts", "golden_trace_512x512.trace",
)

GOLDEN_TRACE_JAX = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "artifacts", "golden_trace_512x512_jax.trace",
)


def trace_gate() -> int:
    """Golden-trace replay gate (the ISSUE 5 acceptance bar): bit-for-bit
    replay identity at threads {1, 2} + the v2 wire loopback, plus the
    warm-solve floor measured on the replay's own tick walls."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from protocol_tpu.trace.replay import replay

    with open(FLOOR_PATH) as fh:
        floors = json.load(fh)
    failures = []
    warm_rep = None
    for threads in (1, 2):
        rep = replay(GOLDEN_TRACE, engine="native-mt", threads=threads)
        print(
            f"trace gate: native-mt:{threads} verified "
            f"{rep['verified_ticks']}/{rep['ticks']} ticks, divergence "
            f"{rep['divergence']}"
        )
        if rep["divergence"] is not None:
            d = rep["divergence"]
            failures.append(
                f"native-mt:{threads} replay diverged at tick {d['tick']} "
                f"({d['n_rows']} rows, first {d['rows'][:8]})"
            )
        if rep["verified_ticks"] != rep["ticks"]:
            failures.append(
                f"native-mt:{threads} verified only "
                f"{rep['verified_ticks']}/{rep['ticks']} ticks"
            )
        warm_rep = rep
    repw = replay(
        GOLDEN_TRACE, engine="native-mt", threads=2, transport="wire-v2"
    )
    print(
        f"trace gate: wire-v2 verified {repw['verified_ticks']}/"
        f"{repw['ticks']} ticks, divergence {repw['divergence']}"
    )
    if repw["divergence"] is not None:
        d = repw["divergence"]
        failures.append(
            f"wire-v2 replay diverged at tick {d['tick']} "
            f"({d['n_rows']} rows)"
        )
    # warm-solve floor on the inproc replay's own tick walls. A replay
    # that diverged at the cold tick has no warm walls — skip the floor
    # math so the DIVERGENCE failures above surface, not a KeyError.
    # NOTE the floor is 2.0x since ISSUE 13: the exact-repair warm path
    # does MORE work at this toy (512) scale than the historical
    # stale-merge (it maintains bit-identity with a from-scratch
    # rebuild), so the 512 ratio is overhead-dominated — the strong
    # warm-generation floor (>= 10x at 16k, 1% churn) lives in
    # ``perf_gate.py --cand``.
    if "warm_median_ms" in warm_rep:
        speedup = warm_rep["cold_ms"] / max(
            warm_rep["warm_median_ms"], 1e-9
        )
        frac = min(warm_rep["assigned"]) / warm_rep["tasks"]
        print(
            f"trace gate: warm median {warm_rep['warm_median_ms']}ms vs "
            f"cold {warm_rep['cold_ms']}ms ({speedup:.1f}x, floor "
            f"{floors['trace_warm_speedup_floor']}x); min assigned frac "
            f"{frac:.3f}"
        )
        if speedup < floors["trace_warm_speedup_floor"]:
            failures.append(
                f"golden-trace warm tick only {speedup:.1f}x faster than "
                f"cold (floor {floors['trace_warm_speedup_floor']}x)"
            )
        if frac < floors["trace_min_assigned_frac"]:
            failures.append(
                f"golden-trace assigned fraction {frac:.3f} below "
                f"{floors['trace_min_assigned_frac']}"
            )
    elif not failures:
        failures.append(
            "golden-trace replay produced no warm ticks to gate"
        )
    if failures:
        for fmsg in failures:
            print(f"PERF GATE FAIL: {fmsg}", file=sys.stderr)
        return 1
    print("trace perf gate OK")
    return 0


def _warm_recompile_failures(recompiles: dict, budget: int) -> list:
    """Failure lines for jit compilations observed after the warm-up
    boundary of the 1%-churn chain (``recompiles`` is a jitwitness
    delta: entry -> compiles since the mark). ANY recompile past the
    budget means a warm tick hit the tracer — the exact 9.5s-per-tick
    stall class the staging pass (jax-retrace) exists to prevent.
    Factored out so the gate's failure path is testable without paying
    a deliberately-mistraced 4096 chain in CI."""
    total = sum(recompiles.values())
    if total <= budget:
        return []
    worst = ", ".join(
        f"{entry.rsplit(':', 1)[-1]} x{count}"
        for entry, count in sorted(recompiles.items())
    )
    return [
        f"warm chain hit the tracer {total} time(s) after warm-up "
        f"(budget {budget}): {worst} — a warm tick must replay the "
        "compiled cache, never retrace"
    ]


def jax_gate() -> int:
    """First-class jax-engine gate (the ISSUE 17 acceptance bar):
    (a) the committed jax golden replays bit-for-bit under engine=jax
    at one device AND across the full host mesh (cross-device-count
    identity IS the D-invariance certificate at replay scale);
    (b) cross-engine A/B — the native golden replayed under native-mt:2
    vs jax stays inside the documented quality tolerances (the two
    engines legitimately pick different seats; what is gated is how
    much quality moves, not bit-identity);
    (c) sharded candidate generation at 4096 tasks is bit-identical
    between devices=1 and devices=4 (cand_p/cand_c/p4t/price), with
    the D=4 path actually taking the shard_map route;
    (d) warm dual carry across a 1%-churn chain beats the compiled
    cold solve by the committed wall and solve-stage floors, with ZERO
    cold candidate passes (the churn-masked repair path, ISSUE 18);
    (d') the repaired structure — merged lists and persisted parts —
    is bit-identical to a from-scratch generation pass on the final
    features at devices=1 AND devices=4 (the repaired==regen oracle
    contract the native engine's repair_topk_candidates_mt honors);
    (e) the jax assigned fraction stays >= 97% of the native engine's
    on the same population (absolute floor when the native toolchain
    is unavailable)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # arm the jit-cache witness: the warm chain in (d) must never hit
    # the tracer after warm-up (scripts/analysis/staging.py is the
    # static twin of this runtime assertion)
    os.environ.setdefault("PROTOCOL_TPU_JIT_WITNESS", "1")
    from protocol_tpu.utils.platform import force_host_cpu

    # the full-mesh replay and the D=4 shard check both need a multi-
    # device host view; must run before anything initializes jax
    force_host_cpu(4)

    import dataclasses

    import numpy as np

    import bench
    from protocol_tpu.ops.cost import CostWeights
    from protocol_tpu.parallel.jax_arena import JaxSolveArena
    from protocol_tpu.trace.replay import compare, replay

    with open(FLOOR_PATH) as fh:
        floors = json.load(fh)
    failures = []

    # ---- (a) jax golden replay identity: 1 device, then full mesh
    for eng in ("jax:1", "jax"):
        rep = replay(GOLDEN_TRACE_JAX, engine=eng)
        print(
            f"jax gate: {eng} verified {rep['verified_ticks']}/"
            f"{rep['ticks']} ticks, divergence {rep['divergence']}"
        )
        if rep["divergence"] is not None:
            d = rep["divergence"]
            failures.append(
                f"{eng} replay diverged at tick {d['tick']} "
                f"({d['n_rows']} rows, first {d['rows'][:8]})"
            )
        if rep["verified_ticks"] != rep["ticks"]:
            failures.append(
                f"{eng} verified only "
                f"{rep['verified_ticks']}/{rep['ticks']} ticks"
            )

    # ---- (b) cross-engine A/B on the NATIVE golden: quality moves,
    # bounded by the committed tolerances
    ab = compare(
        GOLDEN_TRACE,
        {"engine": "native-mt", "threads": 2},
        {"engine": "jax"},
    )
    qd = ab.get("quality_delta", {})
    tasks = ab["a"]["tasks"]
    frac_delta = (
        ab["assigned_min_delta"] / tasks
        if "assigned_min_delta" in ab else 0.0
    )
    print(
        f"jax gate: A/B native-mt:2 vs jax — gap_per_task_delta "
        f"{qd.get('gap_per_task_delta')}, plan_cost_ratio "
        f"{qd.get('plan_cost_ratio_b_over_a')}, churn_ratio_delta "
        f"{qd.get('churn_ratio_delta')}, assigned min frac delta "
        f"{frac_delta:+.4f}"
    )
    if abs(qd.get("gap_per_task_delta", 0.0)) > floors[
        "jax_ab_gap_per_task_delta_max"
    ]:
        failures.append(
            f"A/B gap-per-task delta {qd['gap_per_task_delta']} exceeds "
            f"{floors['jax_ab_gap_per_task_delta_max']}"
        )
    if qd.get("plan_cost_ratio_b_over_a", 1.0) > floors[
        "jax_ab_plan_cost_ratio_max"
    ]:
        failures.append(
            f"A/B plan cost ratio {qd['plan_cost_ratio_b_over_a']} "
            f"exceeds {floors['jax_ab_plan_cost_ratio_max']}"
        )
    if abs(qd.get("churn_ratio_delta", 0.0)) > floors[
        "jax_ab_churn_ratio_delta_max"
    ]:
        failures.append(
            f"A/B churn ratio delta {qd['churn_ratio_delta']} exceeds "
            f"{floors['jax_ab_churn_ratio_delta_max']}"
        )
    if frac_delta < -floors["jax_ab_assigned_min_frac_delta_max"]:
        failures.append(
            f"A/B assigned min frac delta {frac_delta:+.4f} below "
            f"-{floors['jax_ab_assigned_min_frac_delta_max']}"
        )

    # ---- (c) D-invariance bit-check at 4096 (same synth basis as the
    # cand gate: rng(2) providers x rng(3) requirements)
    n = 4096
    w = CostWeights()

    def _pop():
        return (
            bench.synth_providers(np.random.default_rng(2), n),
            bench.synth_requirements(np.random.default_rng(3), n),
        )

    a1 = JaxSolveArena(devices=1)
    ep, er = _pop()
    p1 = a1.solve(ep, er, w)
    a4 = JaxSolveArena(devices=4)
    ep4, er4 = _pop()
    p4 = a4.solve(ep4, er4, w)
    sharded = bool(a4.last_stats.get("gen_sharded"))
    same = (
        bool((a1._cand_p == a4._cand_p).all())
        and bool((a1._cand_c == a4._cand_c).all())
        and bool((p1 == p4).all())
        and bool((a1._price == a4._price).all())
    )
    print(
        f"jax gate: D-invariance at {n} — devices=4 sharded={sharded}, "
        f"bit-identical={same}"
    )
    if not sharded:
        failures.append(
            "devices=4 generation did not take the shard_map path at "
            f"{n} tasks (tile policy regression?)"
        )
    if not same:
        failures.append(
            f"sharded generation at devices=4 is not bit-identical to "
            f"devices=1 at {n} tasks"
        )

    # ---- (c') the acceptance shape: 16k gen-structure D-invariance.
    # Generation ONLY (the ladder's D-independence is already pinned by
    # the full-arena check above and the mesh replay in (a)) — a full
    # 16k solve per device count would double the gate's wall for no
    # added coverage.
    from protocol_tpu.native.arena import _P_SPEC, _R_SPEC, _canon

    n16 = 16384
    ep16 = bench.synth_providers(np.random.default_rng(2), n16)
    er16 = bench.synth_requirements(np.random.default_rng(3), n16)
    pf16 = _canon(ep16, _P_SPEC)
    rf16 = _canon(er16, _R_SPEC)
    g1 = JaxSolveArena(devices=1)
    cp1, cc1, sh1 = g1._gen(pf16, rf16, w)
    g4 = JaxSolveArena(devices=4)
    cp4, cc4, sh4 = g4._gen(pf16, rf16, w)
    same16 = bool((cp1 == cp4).all()) and bool((cc1 == cc4).all())
    print(
        f"jax gate: gen D-invariance at {n16} — devices=4 "
        f"sharded={sh4}, bit-identical={same16}"
    )
    if not sh4:
        failures.append(
            f"devices=4 generation did not take the shard_map path at "
            f"{n16} tasks"
        )
    if not same16:
        failures.append(
            f"sharded generation at devices=4 is not bit-identical to "
            f"devices=1 at {n16} tasks"
        )

    # ---- (d) warm dual carry vs compiled cold on a 1%-churn chain.
    # Task-side churn: provider repricing at k=64 touches ~half the
    # candidate rows (every row listing a repriced provider), which is
    # honest-but-uninformative for the CARRY — requirement churn keeps
    # the changed set near the churned rows, which is what the warm
    # kernel is for. Cold here is invalidate+resolve (compile already
    # paid), so the ratio is pure algorithmic carry, not XLA caching.
    from protocol_tpu.utils import jitwitness

    a1.invalidate()
    t0 = time.perf_counter()
    a1.solve(ep, er, w)
    cold_s = time.perf_counter() - t0
    cold_solve_ms = a1.last_stats["solve_ms"]
    rng = np.random.default_rng(4)
    walls, solves = [], []
    cold_passes = 0
    warm_mark = None
    for tick in range(3):
        rows = rng.choice(n, n // 100, replace=False)
        ram = np.array(er.ram_mb, copy=True)
        ram[rows] = np.maximum(
            256,
            (ram[rows] * rng.uniform(0.8, 1.25, rows.size)).astype(
                ram.dtype
            ),
        )
        er = dataclasses.replace(er, ram_mb=ram)
        t0 = time.perf_counter()
        pw = a1.solve(ep, er, w)
        walls.append(time.perf_counter() - t0)
        solves.append(a1.last_stats["solve_ms"])
        cold_passes += int(a1.last_stats.get("cand_cold_passes", 1))
        if tick == 0:
            # warm-up boundary: the first warm tick may legitimately
            # engage lazily-built kernels (the cleanup budget bucket);
            # every tick after it must run compile-free
            warm_mark = jitwitness.snapshot()
    recompiles = jitwitness.delta(warm_mark)
    print(
        f"jax gate: warm-tick recompiles after warm-up: "
        f"{sum(recompiles.values())} "
        f"(budget {floors['jax_warm_recompiles_max']}, "
        f"entries traced this process: {len(jitwitness.counts())})"
    )
    failures.extend(_warm_recompile_failures(
        recompiles, floors["jax_warm_recompiles_max"]
    ))
    wall_x = cold_s / max(float(np.median(walls)), 1e-9)
    solve_x = cold_solve_ms / max(float(np.median(solves)), 1e-9)
    print(
        f"jax gate: warm chain at {n} (1% churn) — wall {wall_x:.2f}x "
        f"(floor {floors['jax_warm_wall_speedup_floor']}x), solve "
        f"{solve_x:.2f}x (floor {floors['jax_warm_solve_speedup_floor']}x), "
        f"cand_cold_passes {cold_passes}"
    )
    if wall_x < floors["jax_warm_wall_speedup_floor"]:
        failures.append(
            f"warm wall speedup {wall_x:.2f}x below "
            f"{floors['jax_warm_wall_speedup_floor']}x"
        )
    if solve_x < floors["jax_warm_solve_speedup_floor"]:
        failures.append(
            f"warm solve speedup {solve_x:.2f}x below "
            f"{floors['jax_warm_solve_speedup_floor']}x"
        )
    if cold_passes != 0:
        failures.append(
            f"warm chain paid {cold_passes} cold candidate passes — the "
            "churn-masked repair path regressed to regen-is-repair"
        )

    # ---- (d') repaired==regen oracle at D in {1, 4}: the warm chain
    # above ran the churn-masked repair; the structure it carries must
    # be bit-identical — merged lists AND persisted parts — to a
    # from-scratch pass on the final features, at both device counts.
    # This is the jax twin of the native gate's repair-vs-rebuild
    # equality check on repair_topk_candidates_mt.
    rng4 = np.random.default_rng(4)
    for _ in range(3):
        rows = rng4.choice(n, n // 100, replace=False)
        ram = np.array(er4.ram_mb, copy=True)
        ram[rows] = np.maximum(
            256,
            (ram[rows] * rng4.uniform(0.8, 1.25, rows.size)).astype(
                ram.dtype
            ),
        )
        er4 = dataclasses.replace(er4, ram_mb=ram)
        a4.solve(ep4, er4, w)
        if a4.last_stats.get("cand_cold_passes", 1) != 0:
            failures.append(
                "devices=4 warm tick paid a cold candidate pass"
            )
            break
    part_names = (
        "_cand_p", "_cand_c", "_fwd_p", "_fwd_c", "_pool_t", "_pool_c",
    )
    for dcount, arena, epx, erx in ((1, a1, ep, er), (4, a4, ep4, er4)):
        fresh = JaxSolveArena(devices=dcount)
        fresh.solve(epx, erx, w)
        bad = [
            nm for nm in part_names
            if not bool(
                (getattr(arena, nm) == getattr(fresh, nm)).all()
            )
        ]
        print(
            f"jax gate: repair==regen at {n} devices={dcount} — "
            f"bit-identical={not bad}"
        )
        if bad:
            failures.append(
                f"repaired structure diverges from from-scratch regen "
                f"at devices={dcount}: {', '.join(bad)}"
            )

    # ---- (e) assigned fraction vs native on the same population
    jax_frac = int((pw >= 0).sum()) / n
    try:
        from protocol_tpu.native.arena import NativeSolveArena

        na = NativeSolveArena(threads=2)
        epn, ern = _pop()
        pn = na.solve(epn, ern, w)
        nat_frac = int((pn >= 0).sum()) / n
        rel = jax_frac / max(nat_frac, 1e-9)
        print(
            f"jax gate: assigned frac jax {jax_frac:.4f} vs native "
            f"{nat_frac:.4f} (ratio {rel:.4f}, floor "
            f"{floors['jax_min_assigned_vs_native']})"
        )
        if rel < floors["jax_min_assigned_vs_native"]:
            failures.append(
                f"jax assigned fraction only {rel:.4f} of native's "
                f"(floor {floors['jax_min_assigned_vs_native']})"
            )
    except Exception as exc:  # native toolchain absent: absolute floor
        print(
            f"jax gate: native arena unavailable ({exc}); absolute "
            f"assigned floor {floors['jax_min_assigned_frac_abs']}"
        )
        if jax_frac < floors["jax_min_assigned_frac_abs"]:
            failures.append(
                f"jax assigned fraction {jax_frac:.4f} below absolute "
                f"floor {floors['jax_min_assigned_frac_abs']}"
            )

    if failures:
        for fmsg in failures:
            print(f"PERF GATE FAIL: {fmsg}", file=sys.stderr)
        return 1
    print("jax perf gate OK")
    return 0


def quality_gate() -> int:
    """Decision-quality gate (the ISSUE 8 acceptance bar): (a) golden-
    trace replay with the quality plane ON stays bit-for-bit identical
    at threads {1, 2, 4}; (b) the certified duality gap per task stays
    <= ``quality_gap_per_task_max`` (2x the engine eps); (c) every
    unassigned task carries a cause code (zero unexplained); (d) plan
    churn at 1% population churn stays <= ``quality_churn_ratio_max``
    (a synth 1%-churn workload); (e) the instrumented replay stays
    within the existing ``obs_overhead_max_frac`` budget of the
    uninstrumented one (paired alternating runs, median of per-pair
    ratios)."""
    import tempfile

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from protocol_tpu.trace.replay import replay
    from protocol_tpu.trace.synth import synth_trace

    with open(FLOOR_PATH) as fh:
        floors = json.load(fh)
    failures = []
    gap_max = floors["quality_gap_per_task_max"]
    churn_max = floors["quality_churn_ratio_max"]

    # ---- instrumentation overhead within the obs budget, via the
    # SHARED 4k arena-chain experiment (see arena_chain_overhead): at
    # 512-trace scale fixed per-tick Python costs dominate the wall and
    # the percentage is meaningless; at 4k the solve dominates and the
    # budget is the real contract.
    max_frac = floors["obs_overhead_max_frac"]
    within, _ = arena_chain_overhead("quality gate", max_frac)
    if not within:
        failures.append(
            f"quality-plane overhead exceeds the {max_frac:.0%} obs "
            "budget on the 4k arena chain on every attempt"
        )

    rep = None
    for threads in (1, 2, 4):
        rep = replay(GOLDEN_TRACE, engine="native-mt", threads=threads)
        q = rep.get("quality") or {}
        print(
            f"quality gate: native-mt:{threads} divergence "
            f"{rep['divergence']}, gap/task max "
            f"{q.get('gap_per_task_max')} (ceiling {gap_max}), "
            f"unexplained {q.get('unexplained_unassigned')}"
        )
        if rep["divergence"] is not None:
            d = rep["divergence"]
            failures.append(
                f"native-mt:{threads} replay diverged at tick "
                f"{d['tick']} with the quality plane on — "
                "instrumentation may not perturb the matching"
            )
        if not q:
            failures.append(
                f"native-mt:{threads} replay carried no quality "
                "scalars — the plane is dark"
            )
            continue
        if q["gap_per_task_max"] > gap_max:
            failures.append(
                f"certified duality gap {q['gap_per_task_max']}/task "
                f"exceeds the {gap_max} ceiling (2x engine eps)"
            )
        if q["unexplained_unassigned"] != 0:
            failures.append(
                f"{q['unexplained_unassigned']} unassigned task-ticks "
                "carry no cause code — the taxonomy must be total"
            )

    # ---- plan-churn ceiling at 1% population churn (synth workload)
    with tempfile.TemporaryDirectory() as td:
        tp = os.path.join(td, "churn1pct.trace")
        synth_trace(
            tp, n_providers=512, n_tasks=512, ticks=8, churn=0.01,
            seed=5,
        )
        repc = replay(tp, engine="native-mt", threads=2)
        qc = repc.get("quality") or {}
        print(
            f"quality gate: 1%-churn synth churn_ratio mean "
            f"{qc.get('churn_ratio_mean')} max {qc.get('churn_ratio_max')} "
            f"(ceiling {churn_max}), unexplained "
            f"{qc.get('unexplained_unassigned')}"
        )
        if not qc or qc.get("churn_ratio_mean") is None:
            failures.append("1%-churn synth replay carried no churn ratio")
        else:
            if qc["churn_ratio_mean"] > churn_max:
                failures.append(
                    f"plan churn {qc['churn_ratio_mean']} at 1% "
                    f"population churn exceeds the {churn_max} ceiling"
                )
            if qc["unexplained_unassigned"] != 0:
                failures.append(
                    f"{qc['unexplained_unassigned']} unexplained "
                    "unassigned task-ticks on the 1%-churn workload"
                )

    if failures:
        for fmsg in failures:
            print(f"PERF GATE FAIL: {fmsg}", file=sys.stderr)
        return 1
    print("quality perf gate OK")
    return 0


def chaos_gate() -> int:
    """Seeded chaos gate (the ISSUE 9 acceptance bar, grown the
    ISSUE 14 zombie-resume phase) over the committed golden trace.
    Four phases, one seed each — every run replays the identical fault
    train (the schedule is a pure function of the seed, and the
    acceptance claims are exact, not statistical)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # arm the runtime lock-order witness (ISSUE 10): every lock the
    # chaos drill's servers create from here on asserts the committed
    # acquisition order (scripts/analysis/lock_order.toml) live, under
    # the adversarial interleavings the fault train produces. Zero
    # violations is part of this gate's acceptance bar.
    os.environ.setdefault("PROTOCOL_TPU_LOCK_WITNESS", "1")
    from protocol_tpu.utils import lockwitness

    lockwitness.reset()
    from protocol_tpu.faults.harness import run_chaos

    with open(FLOOR_PATH) as fh:
        floors = json.load(fh)
    failures = []
    frac_floor = floors["chaos_min_assigned_frac"]
    stale_bound = int(floors["chaos_max_stale_streak"])

    # ---- phase A: kill + drop + delay + dup + blackout -> warm
    # reconvergence with bit-identical plans
    rep = run_chaos(
        GOLDEN_TRACE, seed=3,
        drop_rate=0.05, delay_rate=0.05, delay_ms=2.0,
        duplicate_rate=0.1,
        kill_at_tick=3, blackout_at_tick=5, blackout_refusals=2,
    )
    print(
        f"chaos gate A (kill/drop/delay/dup/blackout): "
        f"{rep['ticks']} ticks, restarted={rep['restarted']}, "
        f"reopens={rep['client']['reopens']}, "
        f"replayed={rep['client']['replayed_served']}, "
        f"identical={rep['fresh_ticks_identical']}, "
        f"min-assigned={rep['assigned_frac_min']}"
    )
    if not rep["restarted"]:
        failures.append("phase A never killed/restarted the servicer")
    if rep["client"]["reopens"] != 0:
        failures.append(
            f"phase A: {rep['client']['reopens']} full-snapshot "
            "reopens after restart — recovery was not warm"
        )
    if not rep["fresh_ticks_identical"] or not rep[
        "final_tick_identical"
    ]:
        failures.append(
            f"phase A: plans diverged from the fault-free replay at "
            f"ticks {rep['fresh_mismatch_ticks']} — a tick was lost, "
            "double-applied, or the restored arena continued cold"
        )
    if rep["client"]["replayed_served"] < 1:
        failures.append(
            "phase A: the idempotent-retransmit dedup never fired — "
            "the kill window did not exercise the crash protocol"
        )
    if rep["blackout_refusals_served"] < 1:
        failures.append("phase A: the shard blackout never refused")
    if rep["stale_ticks"]:
        failures.append(
            "phase A: stale answers served with no deadline configured"
        )
    if rep["assigned_frac_min"] < frac_floor:
        failures.append(
            f"phase A: assigned fraction {rep['assigned_frac_min']} "
            f"below {frac_floor}"
        )

    # ---- phase B: forced eviction -> the fallback ladder's counted
    # reopen (the one fault whose CONTRACT is the reopen)
    rep_b = run_chaos(GOLDEN_TRACE, seed=4, evict_at_tick=4)
    print(
        f"chaos gate B (forced eviction): reopens="
        f"{rep_b['client']['reopens']}, "
        f"min-assigned={rep_b['assigned_frac_min']}"
    )
    if rep_b["client"]["reopens"] != 1:
        failures.append(
            f"phase B: expected exactly 1 counted reopen after the "
            f"forced eviction, got {rep_b['client']['reopens']}"
        )
    if rep_b["assigned_frac_min"] < frac_floor:
        failures.append(
            f"phase B: assigned fraction {rep_b['assigned_frac_min']} "
            f"below {frac_floor}"
        )

    # ---- phase C: zombie-resume (the ISSUE 14 autonomous-detector
    # bar): SIGSTOP one of 3 REAL servicer processes mid-run — the
    # failure detector must promote it suspect->dead with ZERO
    # driver-owned kill events, re-route its journals along the ring
    # (topology generation bump), and the resumed zombie must find its
    # fencing epoch superseded and be moved:-refused. Zero
    # double-applied ticks (plans bit-identical to the fault-free
    # replay), zero reopens, time-to-detect under the committed floor.
    from protocol_tpu.fleet.loadgen import run_load

    ttd_max = float(floors["chaos_time_to_detect_s_max"])
    zombie_frac_floor = floors["chaos_zombie_min_assigned_frac"]
    rep_z = run_load(
        sessions=6, tenants=3, providers=128, tasks=128, ticks=8,
        churn=0.02, kernel="native-mt:1", shards=2, seed=7,
        processes=3, chaos="seed=7,pause_proc_at_tick=2,pause_proc=1",
        rpc_timeout_s=10.0, max_retries=60, verify_plans=True,
    )
    drill = rep_z.get("drill") or {}
    det = rep_z.get("detector") or {}
    mig_z = rep_z["migration"]
    print(
        f"chaos gate C (zombie-resume): ejected_by_detector="
        f"{drill.get('ejected_by_detector')} ttd="
        f"{det.get('time_to_detect_s')}s journals_rerouted="
        f"{drill.get('journals_rerouted')} zombie_refused="
        f"{drill.get('zombie_fence_refused')} fence_refusals="
        f"{det.get('fence_refusals')} reopens={mig_z['reopens_total']} "
        f"plan_mismatches={mig_z['plan_mismatches_total']} "
        f"false_positives={len(det.get('false_positive_ejections', []))}"
    )
    for err in rep_z["errors"]:
        failures.append(f"phase C: session error: {err}")
    if not drill.get("ejected_by_detector"):
        failures.append(
            "phase C: the paused process was never ejected by the "
            "detector — autonomy is dark (every prior drill was "
            "driver-scripted)"
        )
    if drill.get("journals_rerouted", 0) < 1:
        failures.append(
            "phase C: ejection re-routed no journals — the recovery "
            "path was never exercised"
        )
    if not drill.get("zombie_fence_refused"):
        failures.append(
            "phase C: the resumed zombie was NOT fence-refused — a "
            "paused process could double-serve its old sessions "
            f"(answer: {drill.get('zombie_answer')!r})"
        )
    ttd = det.get("time_to_detect_s")
    if ttd is None or ttd > ttd_max:
        failures.append(
            f"phase C: time-to-detect {ttd}s exceeds the committed "
            f"{ttd_max}s floor"
        )
    if det.get("false_positive_ejections"):
        failures.append(
            f"phase C: detector ejected never-faulted process(es): "
            f"{det['false_positive_ejections']} — flap suppression "
            "failed"
        )
    if mig_z["reopens_total"] != 0:
        failures.append(
            f"phase C: {mig_z['reopens_total']} full-snapshot reopens "
            "— zombie recovery was not warm"
        )
    if mig_z["plan_mismatches_total"] != 0:
        failures.append(
            f"phase C: {mig_z['plan_mismatches_total']} plans diverged "
            "from the fault-free replay — a tick was double-applied "
            "or lost"
        )
    for t, agg in rep_z["tenants"].items():
        if agg["min_assigned_frac"] < zombie_frac_floor:
            failures.append(
                f"phase C: tenant {t} assigned "
                f"{agg['min_assigned_frac']} below {zombie_frac_floor}"
            )
    for pid, viols in (rep_z.get("witness_violations") or {}).items():
        if viols:
            failures.append(
                f"phase C: {len(viols)} lock-witness violation(s) in "
                f"{pid}: {viols[:2]}"
            )

    # ---- phase D: per-tick deadline -> bounded, flagged, counted
    # staleness (the graceful-degradation contract)
    rep_c = run_chaos(
        GOLDEN_TRACE, seed=5, tick_deadline_ms=0.01,
        max_stale_ticks=stale_bound,
    )
    n_stale = len(rep_c["stale_ticks"])
    print(
        f"chaos gate D (deadline degradation): {n_stale} stale ticks, "
        f"max streak {rep_c['max_stale_streak']} (bound {stale_bound}), "
        f"client-counted {rep_c['client']['stale_served']}, "
        f"obs-counted {rep_c['server_stale_obs']}, "
        f"min-assigned {rep_c['assigned_frac_min']}"
    )
    if n_stale == 0:
        failures.append(
            "phase D: the 0.01 ms deadline produced no stale answers — "
            "the watchdog is dark"
        )
    if rep_c["max_stale_streak"] > stale_bound:
        failures.append(
            f"phase D: stale streak {rep_c['max_stale_streak']} "
            f"exceeds the {stale_bound}-tick bound — staleness is not "
            "bounded"
        )
    if rep_c["client"]["stale_served"] != n_stale:
        failures.append(
            "phase D: client-side stale count disagrees with the "
            "flagged responses — degradation is not explicit"
        )
    if sum(rep_c["server_stale_obs"].values()) != n_stale:
        failures.append(
            f"phase D: obs plane counted "
            f"{sum(rep_c['server_stale_obs'].values())} stale ticks "
            f"for {n_stale} served — degraded answers must be counted"
        )
    if rep_c["assigned_frac_min"] < frac_floor:
        failures.append(
            f"phase D: assigned fraction {rep_c['assigned_frac_min']} "
            f"below {frac_floor} — staleness bought too much quality"
        )

    # ---- lock-order witness verdict over the in-process phases
    violations = lockwitness.violations()
    print(
        f"lock witness: {len(violations)} order violation(s) across "
        "chaos phases A/B/D (phase C's verdicts ride the per-process "
        "witness dumps above)"
    )
    if violations:
        failures.append(
            f"lock-order witness recorded {len(violations)} "
            f"violation(s) under chaos: {violations[:3]}"
        )

    if failures:
        for fmsg in failures:
            print(f"PERF GATE FAIL: {fmsg}", file=sys.stderr)
        return 1
    print("chaos perf gate OK")
    return 0


def dfleet_gate() -> int:
    """Distributed-fleet gate (the ISSUE 12 acceptance bar): kill one
    of 3 REAL servicer processes mid-run under seeded drop/delay
    faults; every session must resume warm on a survivor with zero
    client reopens and bounded counted staleness. Phase B drains a
    process by LIVE migration and holds the same bars."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # the runtime lock-order witness runs INSIDE every spawned process
    # (env is inherited); each dumps its verdict at drain/exit and the
    # report joins them — zero violations is part of the bar
    os.environ.setdefault("PROTOCOL_TPU_LOCK_WITNESS", "1")
    from protocol_tpu.fleet.loadgen import run_load

    with open(FLOOR_PATH) as fh:
        floors = json.load(fh)
    frac_floor = floors["dfleet_min_assigned_frac"]
    fairness_floor = floors["dfleet_fairness_floor"]
    stale_max = int(floors["dfleet_max_stale_total"])
    failures = []

    def _check(phase: str, rep: dict, want_key: str) -> None:
        drill = rep.get("drill") or {}
        mig = rep["migration"]
        # the drill retargets to a busy process, so it must have moved
        # REAL state: a kill re-routes journals, a drain live-migrates
        moved_state = (
            drill.get("journals_rerouted", 0)
            if want_key == "killed" else drill.get("migrated", 0)
        )
        if drill.get(want_key) and moved_state < 1:
            failures.append(
                f"phase {phase}: drill fired but moved no session "
                "state — the recovery path was never exercised"
            )
        print(
            f"dfleet gate {phase}: drill={drill} | failovers="
            f"{mig['failovers']} moved={mig['moved_redirects']} "
            f"handoff_waits={mig['handoff_waits']} replayed="
            f"{mig['replayed_total']} stale={mig['stale_total']} "
            f"reopens={mig['reopens_total']} | fairness="
            f"{rep['fairness_index_sessions']} | fleet p99="
            f"{rep['fleet_warm_tick'].get('p99_ms')}ms"
        )
        for err in rep["errors"]:
            failures.append(f"phase {phase}: session error: {err}")
        if not drill.get(want_key):
            failures.append(
                f"phase {phase}: the process drill never fired "
                f"({want_key})"
            )
        if mig["reopens_total"] != 0:
            failures.append(
                f"phase {phase}: {mig['reopens_total']} full-snapshot "
                "reopens — recovery was not warm"
            )
        if mig["stale_total"] > stale_max:
            failures.append(
                f"phase {phase}: {mig['stale_total']} stale ticks "
                f"exceed the {stale_max} bound"
            )
        for t, agg in rep["tenants"].items():
            if agg["min_assigned_frac"] < frac_floor:
                failures.append(
                    f"phase {phase}: tenant {t} assigned "
                    f"{agg['min_assigned_frac']} below {frac_floor}"
                )
            if agg["ticks_done"] == 0:
                failures.append(
                    f"phase {phase}: tenant {t} completed zero ticks"
                )
        if rep["fairness_index_sessions"] < fairness_floor:
            failures.append(
                f"phase {phase}: session fairness "
                f"{rep['fairness_index_sessions']} below "
                f"{fairness_floor}"
            )
        for pid, viols in (rep.get("witness_violations") or {}).items():
            if viols:
                failures.append(
                    f"phase {phase}: {len(viols)} lock-witness "
                    f"violation(s) in {pid}: {viols[:2]}"
                )

    # ---- phase A: kill -9 one of 3 processes mid-run under seeded
    # drop/delay faults -> warm failover along the ring
    rep = run_load(
        sessions=9, tenants=3, providers=256, tasks=256, ticks=8,
        churn=0.02, kernel="native-mt:1", shards=2, seed=1,
        processes=3, restart_at_tick=3, restart_mode="crash",
        chaos="seed=5,drop=0.03,delay=0.05,delay_ms=2,"
              "kill_proc_at_tick=3,kill_proc=1",
    )
    _check("A (kill -9 + faults)", rep, "killed")

    # ---- phase B: live migration (Migrate RPC, moved: redirects),
    # then graceful drain of the emptied process
    rep_b = run_load(
        sessions=6, tenants=2, providers=256, tasks=256, ticks=8,
        churn=0.02, kernel="native-mt:1", shards=2, seed=2,
        processes=3, restart_at_tick=3, restart_mode="drain",
    )
    _check("B (live migrate + drain)", rep_b, "drained")

    if failures:
        for fmsg in failures:
            print(f"PERF GATE FAIL: {fmsg}", file=sys.stderr)
        return 1
    print("dfleet perf gate OK")
    return 0


def dstream_gate() -> int:
    """Distributed event-firehose gate (ISSUE 20). Three phases over
    THREE real servicer processes behind the consistent-hash ring:

    A — every session replays the committed golden distributed stream
        trace (artifacts/golden_dstream_256x256.trace) under seeded
        drop/dup/reorder DELIVERY chaos (every re-delivery is a fresh
        wire tick, so the server's event-seq dedup — not the tick CRC —
        must absorb it), with a mass blackout event fanned into every
        session's firehose mid-run at the sentinel seq tier. Bar: every
        session's final reconciled plan BIT-IDENTICAL to the fault-free
        in-process replay of the same trace + storm, zero reopens, zero
        dropped sources, zero session errors, zero lock-witness
        violations.
    B — SIGKILL one process mid-run with the failure detector armed
        (kill_unannounced: the driver does NOT take the corpse off the
        detector's watch). The detector must eject it autonomously
        (zero false positives), re-route its journals along the ring,
        and the generation-keyed ejection leave storm — one leave per
        event source homed on the corpse — must be absorbed ONLINE by
        the surviving sessions' stream engines (O(churned rows) per
        event; the storm shows up as applied storm events, never as
        reopens). Same bit-identity bar, plus per-tenant assigned
        fraction >= ``dstream_min_assigned_frac`` at the final
        reconcile (providers sized with failover headroom).
    C — clean 3-process throughput floor: fleet-wide server-observed
        events/sec >= ``dstream_fleet_events_per_s_floor`` and
        per-tenant p99 event RPC <= ``dstream_event_p99_us_max``
        (floors committed conservatively below measured, per this
        file's convention)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("PROTOCOL_TPU_LOCK_WITNESS", "1")
    from protocol_tpu.fleet.loadgen import run_events

    with open(FLOOR_PATH) as fh:
        floors = json.load(fh)
    frac_floor = floors["dstream_min_assigned_frac"]
    eps_floor = floors["dstream_fleet_events_per_s_floor"]
    p99_max = floors["dstream_event_p99_us_max"]
    failures = []
    golden = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "artifacts", "golden_dstream_256x256.trace",
    )

    def _check(phase: str, rep: dict, assigned_floor=None) -> None:
        bit = rep.get("bit_identity") or {}
        lad = rep.get("ladder") or {}
        print(
            f"dstream gate {phase}: events={rep['events_total']} "
            f"storms={rep['storm_events_total']} "
            f"fleet_events_per_s={rep['fleet_events_per_s']} | "
            f"bit={bit.get('checked')}/{rep['sessions']} "
            f"mismatches={bit.get('mismatches')} "
            f"skipped={bit.get('skipped')} | ladder={lad} | "
            f"sources={rep['sources']} | drill={rep.get('drill')}"
        )
        for err in rep["errors"]:
            failures.append(f"phase {phase}: session error: {err}")
        if lad.get("reopens", 0) != 0:
            failures.append(
                f"phase {phase}: {lad['reopens']} full-snapshot "
                "reopens — stream failover was not warm"
            )
        if rep["sources"]["dropped"] != 0:
            failures.append(
                f"phase {phase}: {rep['sources']['dropped']} event "
                "sources dropped mid-drill"
            )
        if bit.get("checked", 0) < 1 or bit.get("skipped", 0) != 0:
            failures.append(
                f"phase {phase}: bit-identity covered "
                f"{bit.get('checked', 0)} sessions with "
                f"{bit.get('skipped', 0)} skipped — the witness is "
                "not total"
            )
        if bit.get("mismatches", 0) != 0:
            failures.append(
                f"phase {phase}: {bit['mismatches']} session(s) NOT "
                "bit-identical to the fault-free replay: "
                f"{bit.get('mismatched_sessions')}"
            )
        if assigned_floor is not None:
            for t, agg in rep["tenants"].items():
                a = agg.get("assigned_last_min")
                if a is None or a < assigned_floor:
                    failures.append(
                        f"phase {phase}: tenant {t} final assigned "
                        f"{a} below {assigned_floor}"
                    )
        for pid, viols in (rep.get("witness_violations") or {}).items():
            if viols:
                failures.append(
                    f"phase {phase}: {len(viols)} lock-witness "
                    f"violation(s) in {pid}: {viols[:2]}"
                )

    # ---- phase A: golden trace, delivery chaos, mass blackout fan-out
    rep = run_events(
        sessions=6, tenants=2, providers=256, tasks=256,
        kernel="native-mt:1", reconcile_every=16, shards=2, seed=1,
        processes=3, trace_path=golden,
        chaos="seed=5,drop=0.05,dup=0.05,reorder=0.05",
        mass_at_event=24, mass_frac=0.1,
    )
    _check("A (chaos'd mass fan-out)", rep)
    if rep["storm_events_total"] <= 0:
        failures.append(
            "phase A: the mass blackout fanned out zero storm events"
        )
    mass = rep.get("mass") or {}
    if not mass.get("rows"):
        failures.append(
            f"phase A: the mass event was never armed ({mass})"
        )

    # ---- phase B: SIGKILL + detector ejection -> online leave storm
    rep_b = run_events(
        sessions=6, tenants=2, providers=512, tasks=256, events=48,
        rate_hz=400.0, kernel="native-mt:1", reconcile_every=16,
        shards=2, seed=2, processes=3, detect=True,
        chaos="seed=7,drop=0.02,dup=0.02,kill_proc_at_tick=16,"
              "kill_proc=1",
    )
    _check("B (SIGKILL + ejection storm)", rep_b,
           assigned_floor=frac_floor)
    drill = rep_b.get("drill") or {}
    if not drill.get("killed"):
        failures.append("phase B: the SIGKILL drill never fired")
    if not drill.get("ejected_by_detector"):
        failures.append(
            "phase B: the failure detector never ejected the corpse "
            f"(drill={drill})"
        )
    if not drill.get("storm_posted"):
        failures.append(
            "phase B: the ejection leave storm was never posted"
        )
    if rep_b["storm_events_total"] <= 0:
        failures.append(
            "phase B: the ejection storm fanned out zero leave events"
        )
    fp = (rep_b.get("detector") or {}).get(
        "false_positive_ejections"
    )
    if fp:
        failures.append(
            f"phase B: {len(fp)} false-positive ejection(s): {fp}"
        )

    # ---- phase C: clean 3-process throughput + latency floors
    rep_c = run_events(
        sessions=6, tenants=2, providers=256, tasks=256, events=64,
        rate_hz=2000.0, kernel="native-mt:1", reconcile_every=16,
        shards=2, seed=3, processes=3,
    )
    _check("C (clean throughput)", rep_c, assigned_floor=frac_floor)
    if rep_c["fleet_events_per_s"] < eps_floor:
        failures.append(
            f"phase C: fleet events/sec {rep_c['fleet_events_per_s']} "
            f"below the {eps_floor} floor"
        )
    for t, agg in rep_c["tenants"].items():
        p99 = (agg.get("event_rpc") or {}).get("p99_us")
        if p99 is None or p99 > p99_max:
            failures.append(
                f"phase C: tenant {t} event p99 {p99}us above the "
                f"{p99_max}us cap"
            )

    if failures:
        for fmsg in failures:
            print(f"PERF GATE FAIL: {fmsg}", file=sys.stderr)
        return 1
    print("dstream perf gate OK")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--update-floor", action="store_true")
    ap.add_argument("--wire", action="store_true")
    ap.add_argument("--sinkhorn", action="store_true")
    ap.add_argument("--trace", action="store_true")
    ap.add_argument("--obs", action="store_true")
    ap.add_argument("--fleet", action="store_true")
    ap.add_argument("--quality", action="store_true")
    ap.add_argument("--chaos", action="store_true")
    ap.add_argument("--dfleet", action="store_true")
    ap.add_argument("--dstream", action="store_true")
    ap.add_argument("--cand", action="store_true")
    ap.add_argument("--stream", action="store_true")
    ap.add_argument("--simd", action="store_true")
    ap.add_argument("--jax", action="store_true")
    args = ap.parse_args()

    if args.jax:
        return jax_gate()

    if args.simd:
        return simd_gate()
    if args.stream:
        return stream_gate()
    if args.cand:
        return cand_gate()
    if args.wire:
        return wire_gate()
    if args.sinkhorn:
        return sinkhorn_gate()
    if args.trace:
        return trace_gate()
    if args.obs:
        return obs_gate()
    if args.fleet:
        return fleet_gate()
    if args.quality:
        return quality_gate()
    if args.chaos:
        return chaos_gate()
    if args.dfleet:
        return dfleet_gate()
    if args.dstream:
        return dstream_gate()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    import bench
    from protocol_tpu import native
    from protocol_tpu.ops.cost import CostWeights

    rng = np.random.default_rng(0)
    ep = bench.synth_providers(rng, N)
    er = bench.synth_requirements(rng, N)
    w = CostWeights()

    # warmup (first call pays the build/load)
    native.fused_topk_candidates(ep, er, w, k=16)

    iters = 3
    t0 = time.perf_counter()
    for _ in range(iters):
        cand_p, cand_c = native.fused_topk_candidates(ep, er, w, k=64)
        p4t = native.auction_sparse(cand_p, cand_c, num_providers=N)
    wall = (time.perf_counter() - t0) / iters
    assigned = int((p4t >= 0).sum())
    rate = assigned / wall
    print(f"native engine {N}x{N}: {wall * 1e3:.1f} ms/solve, "
          f"{rate:,.0f} assignments/s ({assigned}/{N} assigned)")

    failures = []

    # ---- throughput floor
    if args.update_floor:
        # update ONLY the native-throughput keys: the wire_v2_* floors are
        # fixed acceptance criteria, not host-measured, and clobbering
        # them would break the --wire gate on the next CI run
        with open(FLOOR_PATH) as fh:
            floors = json.load(fh)
        floors["native_2048x2048_assignments_per_s_floor"] = round(rate * 0.25)
        floors["measured_assignments_per_s"] = round(rate)
        with open(FLOOR_PATH, "w") as fh:
            json.dump(floors, fh, indent=1)
        print(f"floor updated: {FLOOR_PATH}")
    else:
        with open(FLOOR_PATH) as fh:
            floor = json.load(fh)["native_2048x2048_assignments_per_s_floor"]
        print(f"floor: {floor:,.0f} assignments/s")
        if rate < floor:
            failures.append(
                f"throughput {rate:,.0f} below floor {floor:,.0f} assignments/s"
            )

    # ---- parity vs greedy on the same candidate surface
    cost = np.full((N, N), 1e9, np.float32)
    for t in range(N):
        row = cand_p[t]
        ok = row >= 0
        cost[row[ok], t] = cand_c[t][ok]
    greedy = native.greedy_assign(cost)
    n_greedy = int((greedy >= 0).sum())
    cost_greedy = float(sum(cost[p, t] for t, p in enumerate(greedy) if p >= 0))
    cost_auction = float(sum(cost[p, t] for t, p in enumerate(p4t) if p >= 0))
    print(f"parity: auction {assigned} @ {cost_auction:,.1f} vs "
          f"greedy {n_greedy} @ {cost_greedy:,.1f}")
    if assigned < n_greedy:
        failures.append(f"auction assigned {assigned} < greedy {n_greedy}")
    if assigned == n_greedy and cost_auction > cost_greedy * 1.02 + 1.0:
        failures.append(
            f"auction cost {cost_auction:,.1f} exceeds 102% of greedy "
            f"{cost_greedy:,.1f}"
        )

    # ---- the -mt determinism contract (thread-count invariance)
    p4t_mt1, _, _ = native.auction_sparse_mt(cand_p, cand_c, num_providers=N, threads=1)
    p4t_mt2, _, _ = native.auction_sparse_mt(cand_p, cand_c, num_providers=N, threads=2)
    if not np.array_equal(p4t_mt1, p4t_mt2):
        failures.append("native-mt matching differs between threads=1 and threads=2")
    n_mt = int((p4t_mt2 >= 0).sum())
    print(f"native-mt: {n_mt}/{N} assigned, thread-invariant: "
          f"{np.array_equal(p4t_mt1, p4t_mt2)}")
    if n_mt < n_greedy:
        failures.append(f"native-mt assigned {n_mt} < greedy {n_greedy}")

    if failures:
        for f in failures:
            print(f"PERF GATE FAIL: {f}", file=sys.stderr)
        return 1
    print("perf gate OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
