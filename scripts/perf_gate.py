#!/usr/bin/env python
"""CI perf floor for the native assignment engine (VERDICT r5 "what's
missing" #4: a solver regression like round 4's 0.2x warm bug would merge
clean without a bench gate).

Runs a small (2k x 2k) native-engine solve and FAILS (exit 1) when:

  - end-to-end throughput drops below the stored floor
    (scripts/perf_floor.json — conservative: ~25% of the slowest
    observed CI-class 2-core host, so machine jitter never false-fails
    while a 4x regression cannot merge), or
  - parity vs the greedy oracle breaks: the auction must assign at least
    as many tasks as greedy and at no more than 102% of greedy's total
    cost on its own candidate surface, or
  - the multi-threaded engine's matching is not bit-identical to
    threads=1 (the -mt determinism contract).

Usage: python scripts/perf_gate.py [--update-floor]
(--update-floor rewrites perf_floor.json to 25% of this machine's
measured rate — run on the slowest supported host class, then commit.)
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

FLOOR_PATH = os.path.join(os.path.dirname(__file__), "perf_floor.json")
N = 2048


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--update-floor", action="store_true")
    args = ap.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    import bench
    from protocol_tpu import native
    from protocol_tpu.ops.cost import CostWeights

    rng = np.random.default_rng(0)
    ep = bench.synth_providers(rng, N)
    er = bench.synth_requirements(rng, N)
    w = CostWeights()

    # warmup (first call pays the build/load)
    native.fused_topk_candidates(ep, er, w, k=16)

    iters = 3
    t0 = time.perf_counter()
    for _ in range(iters):
        cand_p, cand_c = native.fused_topk_candidates(ep, er, w, k=64)
        p4t = native.auction_sparse(cand_p, cand_c, num_providers=N)
    wall = (time.perf_counter() - t0) / iters
    assigned = int((p4t >= 0).sum())
    rate = assigned / wall
    print(f"native engine {N}x{N}: {wall * 1e3:.1f} ms/solve, "
          f"{rate:,.0f} assignments/s ({assigned}/{N} assigned)")

    failures = []

    # ---- throughput floor
    if args.update_floor:
        with open(FLOOR_PATH, "w") as fh:
            json.dump(
                {
                    "native_2048x2048_assignments_per_s_floor": round(rate * 0.25),
                    "measured_assignments_per_s": round(rate),
                },
                fh, indent=1,
            )
        print(f"floor updated: {FLOOR_PATH}")
    else:
        with open(FLOOR_PATH) as fh:
            floor = json.load(fh)["native_2048x2048_assignments_per_s_floor"]
        print(f"floor: {floor:,.0f} assignments/s")
        if rate < floor:
            failures.append(
                f"throughput {rate:,.0f} below floor {floor:,.0f} assignments/s"
            )

    # ---- parity vs greedy on the same candidate surface
    cost = np.full((N, N), 1e9, np.float32)
    for t in range(N):
        row = cand_p[t]
        ok = row >= 0
        cost[row[ok], t] = cand_c[t][ok]
    greedy = native.greedy_assign(cost)
    n_greedy = int((greedy >= 0).sum())
    cost_greedy = float(sum(cost[p, t] for t, p in enumerate(greedy) if p >= 0))
    cost_auction = float(sum(cost[p, t] for t, p in enumerate(p4t) if p >= 0))
    print(f"parity: auction {assigned} @ {cost_auction:,.1f} vs "
          f"greedy {n_greedy} @ {cost_greedy:,.1f}")
    if assigned < n_greedy:
        failures.append(f"auction assigned {assigned} < greedy {n_greedy}")
    if assigned == n_greedy and cost_auction > cost_greedy * 1.02 + 1.0:
        failures.append(
            f"auction cost {cost_auction:,.1f} exceeds 102% of greedy "
            f"{cost_greedy:,.1f}"
        )

    # ---- the -mt determinism contract (thread-count invariance)
    p4t_mt1, _, _ = native.auction_sparse_mt(cand_p, cand_c, num_providers=N, threads=1)
    p4t_mt2, _, _ = native.auction_sparse_mt(cand_p, cand_c, num_providers=N, threads=2)
    if not np.array_equal(p4t_mt1, p4t_mt2):
        failures.append("native-mt matching differs between threads=1 and threads=2")
    n_mt = int((p4t_mt2 >= 0).sum())
    print(f"native-mt: {n_mt}/{N} assigned, thread-invariant: "
          f"{np.array_equal(p4t_mt1, p4t_mt2)}")
    if n_mt < n_greedy:
        failures.append(f"native-mt assigned {n_mt} < greedy {n_greedy}")

    if failures:
        for f in failures:
            print(f"PERF GATE FAIL: {f}", file=sys.stderr)
        return 1
    print("perf gate OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
