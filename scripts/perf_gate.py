#!/usr/bin/env python
"""CI perf floor for the native assignment engine (VERDICT r5 "what's
missing" #4: a solver regression like round 4's 0.2x warm bug would merge
clean without a bench gate).

Runs a small (2k x 2k) native-engine solve and FAILS (exit 1) when:

  - end-to-end throughput drops below the stored floor
    (scripts/perf_floor.json — conservative: ~25% of the slowest
    observed CI-class 2-core host, so machine jitter never false-fails
    while a 4x regression cannot merge), or
  - parity vs the greedy oracle breaks: the auction must assign at least
    as many tasks as greedy and at no more than 102% of greedy's total
    cost on its own candidate surface, or
  - the multi-threaded engine's matching is not bit-identical to
    threads=1 (the -mt determinism contract).

With ``--wire`` it instead runs the loopback WIRE-PATH floor (ISSUE 2):
a 16k x 16k marketplace with 1% row churn over a real localhost gRPC
seam — the v2 delta tick (serialize + RPC + warm native-mt solve) must
beat the v1 full-snapshot tick by >= 3x end-to-end with >= 20x fewer
per-tick wire bytes, and the steady-state matching must keep >= 97% of
tasks assigned. A wire regression (a chatty codec, a session-protocol
break, a warm-solve regression behind the seam) cannot merge on green
unit tests alone.

Usage: python scripts/perf_gate.py [--update-floor] [--wire]
(--update-floor rewrites perf_floor.json to 25% of this machine's
measured rate — run on the slowest supported host class, then commit.)
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

FLOOR_PATH = os.path.join(os.path.dirname(__file__), "perf_floor.json")
N = 2048


def wire_gate() -> int:
    """Loopback wire-path floor: v2 delta sessions vs v1 full snapshots
    at 16k x 16k with 1% churn (the ISSUE 2 acceptance bar)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import bench

    with open(FLOOR_PATH) as fh:
        floors = json.load(fh)
    res = bench.run_wire_bench(P=16384, T=16384, churn=0.01,
                               ticks=4, warmup=3)
    failures = []
    speedup_floor = floors["wire_v2_vs_v1_speedup_floor"]
    bytes_floor = floors["wire_v2_bytes_ratio_floor"]
    assigned_floor = floors["wire_v2_min_assigned_frac"]
    print(f"wire gate: v2 speedup {res['v2_speedup']}x "
          f"(floor {speedup_floor}x), bytes ratio {res['v2_bytes_ratio']}x "
          f"(floor {bytes_floor}x)")
    if res["v2_speedup"] < speedup_floor:
        failures.append(
            f"v2 delta tick only {res['v2_speedup']}x faster than v1 "
            f"full snapshot (floor {speedup_floor}x)"
        )
    if res["v2_bytes_ratio"] < bytes_floor:
        failures.append(
            f"v2 per-tick wire bytes only {res['v2_bytes_ratio']}x "
            f"smaller than v1 (floor {bytes_floor}x)"
        )
    for mode in ("v1", "v2"):
        frac = min(res["modes"][mode]["tick_assigned"]) / res["T"]
        print(f"wire gate: {mode} min assigned frac {frac:.3f}")
        if frac < assigned_floor:
            failures.append(
                f"{mode} steady-state assigned fraction {frac:.3f} below "
                f"{assigned_floor} — the wire win must not be bought with "
                "matching quality"
            )
    if failures:
        for f in failures:
            print(f"PERF GATE FAIL: {f}", file=sys.stderr)
        return 1
    print("wire perf gate OK")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--update-floor", action="store_true")
    ap.add_argument("--wire", action="store_true")
    args = ap.parse_args()

    if args.wire:
        return wire_gate()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    import bench
    from protocol_tpu import native
    from protocol_tpu.ops.cost import CostWeights

    rng = np.random.default_rng(0)
    ep = bench.synth_providers(rng, N)
    er = bench.synth_requirements(rng, N)
    w = CostWeights()

    # warmup (first call pays the build/load)
    native.fused_topk_candidates(ep, er, w, k=16)

    iters = 3
    t0 = time.perf_counter()
    for _ in range(iters):
        cand_p, cand_c = native.fused_topk_candidates(ep, er, w, k=64)
        p4t = native.auction_sparse(cand_p, cand_c, num_providers=N)
    wall = (time.perf_counter() - t0) / iters
    assigned = int((p4t >= 0).sum())
    rate = assigned / wall
    print(f"native engine {N}x{N}: {wall * 1e3:.1f} ms/solve, "
          f"{rate:,.0f} assignments/s ({assigned}/{N} assigned)")

    failures = []

    # ---- throughput floor
    if args.update_floor:
        # update ONLY the native-throughput keys: the wire_v2_* floors are
        # fixed acceptance criteria, not host-measured, and clobbering
        # them would break the --wire gate on the next CI run
        with open(FLOOR_PATH) as fh:
            floors = json.load(fh)
        floors["native_2048x2048_assignments_per_s_floor"] = round(rate * 0.25)
        floors["measured_assignments_per_s"] = round(rate)
        with open(FLOOR_PATH, "w") as fh:
            json.dump(floors, fh, indent=1)
        print(f"floor updated: {FLOOR_PATH}")
    else:
        with open(FLOOR_PATH) as fh:
            floor = json.load(fh)["native_2048x2048_assignments_per_s_floor"]
        print(f"floor: {floor:,.0f} assignments/s")
        if rate < floor:
            failures.append(
                f"throughput {rate:,.0f} below floor {floor:,.0f} assignments/s"
            )

    # ---- parity vs greedy on the same candidate surface
    cost = np.full((N, N), 1e9, np.float32)
    for t in range(N):
        row = cand_p[t]
        ok = row >= 0
        cost[row[ok], t] = cand_c[t][ok]
    greedy = native.greedy_assign(cost)
    n_greedy = int((greedy >= 0).sum())
    cost_greedy = float(sum(cost[p, t] for t, p in enumerate(greedy) if p >= 0))
    cost_auction = float(sum(cost[p, t] for t, p in enumerate(p4t) if p >= 0))
    print(f"parity: auction {assigned} @ {cost_auction:,.1f} vs "
          f"greedy {n_greedy} @ {cost_greedy:,.1f}")
    if assigned < n_greedy:
        failures.append(f"auction assigned {assigned} < greedy {n_greedy}")
    if assigned == n_greedy and cost_auction > cost_greedy * 1.02 + 1.0:
        failures.append(
            f"auction cost {cost_auction:,.1f} exceeds 102% of greedy "
            f"{cost_greedy:,.1f}"
        )

    # ---- the -mt determinism contract (thread-count invariance)
    p4t_mt1, _, _ = native.auction_sparse_mt(cand_p, cand_c, num_providers=N, threads=1)
    p4t_mt2, _, _ = native.auction_sparse_mt(cand_p, cand_c, num_providers=N, threads=2)
    if not np.array_equal(p4t_mt1, p4t_mt2):
        failures.append("native-mt matching differs between threads=1 and threads=2")
    n_mt = int((p4t_mt2 >= 0).sum())
    print(f"native-mt: {n_mt}/{N} assigned, thread-invariant: "
          f"{np.array_equal(p4t_mt1, p4t_mt2)}")
    if n_mt < n_greedy:
        failures.append(f"native-mt assigned {n_mt} < greedy {n_greedy}")

    if failures:
        for f in failures:
            print(f"PERF GATE FAIL: {f}", file=sys.stderr)
        return 1
    print("perf gate OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
