#!/usr/bin/env python
"""Ladder-#4 COLD re-measurement on the modern stack (ISSUE 16).

SCALING.md's 1M x 1M story predates both the persistent candidate
structure (PR 13) and the ISA-dispatched vector pipeline (ISSUE 16):
the stale rows extrapolate stage A from the 793 s jax-on-CPU generation
wall at 65k. This script retires them with MEASURED rows:

  rung rows   bucketed cold candidate generation (fused + capability
              pruner + block-skip) at 65k / 262k — scalar AND widest
              vector at 65k so the speedup over the old wall is a row,
              not a claim
  cold 1M     a NativeSolveArena cold solve at the full 1M x 1M shape:
              bucketed vector gen + bounded eps-ladder auction
              (eps 4.0 -> 1.0, the stageb_1m_smoke convention)
  warm 1M     ONE 1%-churn batch tick on the same arena (the repair
              kernel's transposed pass at shape; zero cold passes)
  stream 1M   single-provider heartbeat events through the
              StreamEngine on the same 1M arena (p50/p99 apply+repair
              latency, zero cold passes, closing reconcile)

Every row is APPENDED to the artifact as it completes (kill-proof, as
in PR 1) and tagged with the runtime ISA. The ladder1m_* floors in
perf_floor.json are checked HERE — the run is far too long for the CI
perf-gate job, so this script is the gate for its own rows.

Population: bench.synth_providers(rng(2)) x synth_requirements(rng(3))
— the same basis as every cand_*/simd_* floor.

    PROTOCOL_TPU_NATIVE_ISA=auto python scripts/cold_ladder_1m.py
    python scripts/cold_ladder_1m.py --rungs 65536 --size 0   # rungs only
"""
import argparse
import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

import bench  # noqa: E402
from protocol_tpu import native  # noqa: E402
from protocol_tpu.ops.cost import CostWeights  # noqa: E402
from protocol_tpu.utils.artifacts import append_jsonl  # noqa: E402

FLOOR_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "perf_floor.json")


def _pop(n: int):
    ep = bench.synth_providers(np.random.default_rng(2), n)
    er = bench.synth_requirements(np.random.default_rng(3), n)
    return ep, er


def _gen_row(ep, er, w, n: int, isa: str, emit) -> float:
    """One bucketed cold-generation rung at the given ISA; returns wall."""
    eff = native.set_isa(isa)
    st: dict = {}
    t0 = time.perf_counter()
    native.fused_topk_candidates(
        ep, er, w, k=64, threads=1, bucketed=True, stats=st
    )
    wall = time.perf_counter() - t0
    cells = float(n) * n
    emit({
        "kind": "gen", "n": n, "isa": eff, "threads": 1,
        "wall_s": round(wall, 1),
        "visited_frac": round(st["gen_visited"] / cells, 4),
        "visited_cells_per_s": int(st["gen_visited"] / wall),
        "pruned_rows": st["gen_pruned_rows"],
        "fallback_rows": st["gen_fallback_rows"],
    })
    return wall


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--rungs", default="65536,262144",
        help="comma-separated gen-only rung sizes (vector ISA; the "
        "first rung also runs the scalar referee for the speedup row)",
    )
    ap.add_argument("--size", type=int, default=1_000_000,
                    help="full arena shape (0 skips the 1M phases)")
    ap.add_argument("--events", type=int, default=256,
                    help="heartbeat events for the stream phase")
    ap.add_argument("--churn", type=float, default=0.01)
    ap.add_argument(
        "--artifact", default="artifacts/cold_ladder_rows.jsonl",
        help="JSONL file each row is APPENDED to as it completes "
        "(kill-proof). Empty string disables.",
    )
    args = ap.parse_args()

    with open(FLOOR_PATH) as fh:
        floors = json.load(fh)
    failures: list = []

    def emit(row: dict) -> None:
        print(json.dumps(row), flush=True)
        append_jsonl(args.artifact, row)

    native.load()
    vec = native.set_isa(native.isa_request() or "auto")
    w = CostWeights()
    print(f"# cold ladder: vector isa={vec}", file=sys.stderr, flush=True)

    # ---- gen-only rungs: the candidate-generation wall vs shape
    rungs = [int(r) for r in args.rungs.split(",") if r]
    for i, n in enumerate(rungs):
        t0 = time.perf_counter()
        ep, er = _pop(n)
        print(f"# rung {n}: population built {time.perf_counter()-t0:.1f}s",
              file=sys.stderr, flush=True)
        wall_v = _gen_row(ep, er, w, n, vec, emit)
        if i == 0 and vec != "scalar":
            wall_s = _gen_row(ep, er, w, n, "scalar", emit)
            emit({
                "kind": "gen_speedup", "n": n, "vector_isa": vec,
                "scalar_s": round(wall_s, 1), "vector_s": round(wall_v, 1),
                "speedup": round(wall_s / max(wall_v, 1e-9), 2),
            })
            native.set_isa(vec)
        del ep, er

    if args.size <= 0:
        return _verdict(failures)

    # ---- the full shape: one arena, three measurements
    from protocol_tpu.native.arena import NativeSolveArena
    from protocol_tpu.proto import wire
    from protocol_tpu.stream.engine import StreamEngine
    from protocol_tpu.stream.events import StreamEvent
    from protocol_tpu.trace import format as tfmt

    n = args.size
    t0 = time.perf_counter()
    ep, er = _pop(n)
    print(f"# {n}: population built {time.perf_counter()-t0:.1f}s",
          file=sys.stderr, flush=True)

    # eps 4.0 -> 1.0: the bounded cold ladder every prior 1M artifact
    # used (stageb_1m_smoke, warm_chain_1m) — completeness evidence at
    # this eps is the smoke's 99.97%
    arena = NativeSolveArena(threads=0, eps_start=4.0, eps_end=1.0,
                             event_max_bids=4096)
    t0 = time.perf_counter()
    p4t = arena.solve(ep, er, w)
    wall = time.perf_counter() - t0
    st = arena.last_stats
    cells = float(n) * n
    gen_s = st["gen_ms"] / 1e3
    visited = st.get("eng_gen_visited")
    cold_row = {
        "kind": "cold", "n": n, "isa": st["native_isa"],
        "wall_s": round(wall, 1),
        "gen_s": round(gen_s, 1),
        "solve_s": round(st["solve_ms"] / 1e3, 1),
        "visited_frac":
            round(visited / cells, 4) if visited is not None else None,
        "assigned": int((p4t >= 0).sum()),
        "assigned_frac": round(int((p4t >= 0).sum()) / n, 4),
    }
    emit(cold_row)
    if gen_s > floors["ladder1m_cold_gen_s_max"]:
        failures.append(
            f"1M cold gen {gen_s:.0f}s above ceiling "
            f"{floors['ladder1m_cold_gen_s_max']}s"
        )
    if cold_row["assigned_frac"] < floors["ladder1m_min_assigned_frac"]:
        failures.append(
            f"1M cold assigned frac {cold_row['assigned_frac']} below "
            f"{floors['ladder1m_min_assigned_frac']}"
        )

    # ---- one 1%-churn warm batch tick (the repair kernel at shape)
    rng = np.random.default_rng(4)
    rows = rng.choice(n, max(int(n * args.churn), 1), replace=False)
    price = np.array(ep.price, copy=True)
    load = np.array(ep.load, copy=True)
    price[rows] = rng.uniform(0.5, 4.0, rows.size).astype(np.float32)
    load[rows] = rng.uniform(0, 1, rows.size).astype(np.float32)
    ep2 = dataclasses.replace(ep, price=price, load=load)
    t0 = time.perf_counter()
    p4t = arena.solve(ep2, er, w)
    wall = time.perf_counter() - t0
    st = arena.last_stats
    warm_row = {
        "kind": "warm", "n": n, "isa": st["native_isa"],
        "churn": args.churn,
        "wall_s": round(wall, 1),
        "repair_s": round(st["gen_ms"] / 1e3, 1),
        "solve_s": round(st["solve_ms"] / 1e3, 1),
        "cold_passes": st["cand_cold_passes"],
        "assigned_frac": round(int((p4t >= 0).sum()) / n, 4),
    }
    emit(warm_row)
    if warm_row["cold_passes"] != 0:
        failures.append(
            f"1M warm tick ran {warm_row['cold_passes']} full-matrix "
            "candidate passes (want 0)"
        )
    if wall > floors["ladder1m_warm_tick_s_max"]:
        failures.append(
            f"1M warm tick {wall:.0f}s above ceiling "
            f"{floors['ladder1m_warm_tick_s_max']}s"
        )

    # ---- streamed single-provider heartbeats on the same 1M arena
    se = StreamEngine(arena, w, reconcile_every=10 ** 9)
    p_cols = wire.canon_columns(ep2, tfmt.P_TRACE_DTYPES)
    # canon may hand back views of ep2's columns: copy before mutating
    p_cols["price"] = p_cols["price"].copy()
    p_cols["load"] = p_cols["load"].copy()
    hb = rng.choice(n, args.events, replace=False)
    walls = []
    cold_passes = 0
    for i, r in enumerate(hb.tolist()):
        rr = np.asarray([r], np.int32)
        p_cols["price"][rr] = rng.uniform(0.5, 4.0, 1).astype(np.float32)
        p_cols["load"][rr] = rng.uniform(0, 1, 1).astype(np.float32)
        ev = StreamEvent(
            kind="heartbeat", source=f"p{r}", seq=0,
            provider_rows=rr,
            p_cols={nm: a[rr] for nm, a in p_cols.items()},
            task_rows=np.zeros(0, np.int32), r_cols={},
        )
        t0 = time.perf_counter()
        res = se.apply(ev)
        walls.append((time.perf_counter() - t0) * 1e3)
        cold_passes += int(res.stats.get("cand_cold_passes", 0))
    walls_a = np.asarray(walls)
    t0 = time.perf_counter()
    recon = se.reconcile()
    recon_s = time.perf_counter() - t0
    p99 = float(np.percentile(walls_a, 99))
    stream_row = {
        "kind": "stream", "n": n, "isa": native.current_isa(),
        "events": args.events,
        "apply_p50_ms": round(float(np.percentile(walls_a, 50)), 1),
        "apply_p99_ms": round(p99, 1),
        "apply_max_ms": round(float(walls_a.max()), 1),
        "cold_passes": cold_passes,
        "reconcile_s": round(recon_s, 1),
        "assigned_frac": round(int((recon.plan >= 0).sum()) / n, 4),
    }
    emit(stream_row)
    if cold_passes != 0:
        failures.append(
            f"1M stream ran {cold_passes} full-matrix passes (want 0)"
        )
    if p99 > floors["ladder1m_stream_p99_ms_max"]:
        failures.append(
            f"1M stream apply p99 {p99:.0f}ms above ceiling "
            f"{floors['ladder1m_stream_p99_ms_max']}ms"
        )
    if stream_row["assigned_frac"] < floors["ladder1m_min_assigned_frac"]:
        failures.append(
            f"1M stream reconcile assigned frac "
            f"{stream_row['assigned_frac']} below "
            f"{floors['ladder1m_min_assigned_frac']}"
        )
    return _verdict(failures)


def _verdict(failures: list) -> int:
    if failures:
        for f in failures:
            print(f"LADDER FLOOR FAIL: {f}", file=sys.stderr)
        return 1
    print("cold ladder floors OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
