#!/usr/bin/env python
"""Ladder-#4 steady state: a WARM CHAIN at the full 1M x 1M shape
(VERDICT r4 item 6's done-bar).

N consecutive churn -> warm-solve steps on the 8-device mesh, carrying
the full dual state (prices + retirement mask) across solves, reporting
per-step wall, rounds, and completeness — the evidence that steady-state
warm cost stays BOUNDED across a chain (no price-ratchet drift, no
per-step tail re-fight), which is the 10 s-cadence argument at 1M.

Synthetic uniform candidates as in stageb_1m_smoke.py: execution
evidence at shape (quality evidence lives in the 65k real-feature runs).

    python scripts/warm_chain_1m.py [--steps 10] [--churn 0.01]
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from protocol_tpu.utils.platform import force_host_cpu  # noqa: E402

force_host_cpu(8)

import numpy as np  # noqa: E402
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from protocol_tpu.parallel import (  # noqa: E402
    assign_auction_sparse_scaled_sharded,
    assign_auction_sparse_warm_sharded,
    make_mesh,
)



def run_native_chain(args, cand_p, cand_c, P, T, eps_end, emit) -> None:
    """The chain on the multi-threaded native engine: one cold eps-ladder
    solve, then churn -> single-phase warm solves carrying prices + the
    retirement mask + the previous matching (the same dual-state shape the
    jax chain carries across assign_auction_sparse_warm_sharded)."""
    from protocol_tpu import native

    native.load()
    isa = native.current_isa()  # provenance: rows are ISA-tagged
    t0 = time.time()
    p4t, price, retired = native.auction_sparse_mt(
        cand_p, cand_c, num_providers=P,
        eps_start=4.0, eps_end=eps_end, threads=args.threads,
    )
    emit({
        "step": 0, "kind": "cold", "engine": "native-mt", "isa": isa,
        "threads": args.threads, "wall_s": round(time.time() - t0, 1),
        "assigned": int((p4t >= 0).sum()),
        "retired": int(retired.sum()),
        "price_max": round(float(price.max()), 3),
    })

    n_churn = max(int(T * args.churn), 1)
    churn_rng = np.random.default_rng(7)
    for step in range(1, args.steps + 1):
        idx = churn_rng.choice(T, size=n_churn, replace=False)
        seeds = p4t.copy()
        seeds[idx] = -1
        retired[idx] = False  # churned tasks are "new" work
        t0 = time.time()
        p4t, price, retired = native.auction_sparse_mt(
            cand_p, cand_c, num_providers=P,
            eps_start=eps_end, eps_end=eps_end, threads=args.threads,
            price=price, retired=retired, seed_provider_for_task=seeds,
        )
        wall = time.time() - t0
        pos = p4t[p4t >= 0]
        emit({
            "step": step, "kind": "warm", "engine": "native-mt", "isa": isa,
            "threads": args.threads, "wall_s": round(wall, 1),
            "assigned": int((p4t >= 0).sum()),
            "injective": bool(np.unique(pos).size == pos.size),
            "retired": int(retired.sum()),
            "price_max": round(float(price.max()), 3),
        })


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--churn", type=float, default=0.01)
    ap.add_argument("--size", type=int, default=1_000_000)
    ap.add_argument(
        "--engine", choices=["jax", "native-mt"], default="jax",
        help="native-mt runs the chain through the multi-threaded C++ "
        "auction (auction_sparse_mt) carrying the same dual state — the "
        "CPU-host answer to the 330-560 s/step jax-on-CPU chain",
    )
    ap.add_argument("--threads", type=int, default=0, help="0 = all cores")
    ap.add_argument(
        "--artifact", default="artifacts/warm_chain_rows.jsonl",
        help="JSONL file each step row is APPENDED to as it completes "
        "(kill-proof). Empty string disables.",
    )
    ap.add_argument(
        "--trace", default="",
        help="flight-recorder trace whose snapshot supplies the chain's "
        "candidate structure (real features -> fused top-K lists) "
        "instead of the uniform synthetic candidates; --size is then "
        "taken from the trace",
    )
    args = ap.parse_args()

    from protocol_tpu.utils.artifacts import append_jsonl

    def emit(row: dict) -> None:
        print(json.dumps(row), flush=True)
        append_jsonl(args.artifact, row)

    T = P = args.size
    K = 80
    EPS_END = 1.0  # matches the smoke's bounded cold ladder
    t0 = time.time()
    if args.trace:
        # real-feature candidates from a recorded population: the trace
        # snapshot's encodings through the fused native pass (the chain
        # then measures warm-solve behavior on a shareable fleet)
        from protocol_tpu import native
        from protocol_tpu.ops.cost import CostWeights
        from protocol_tpu.trace import format as tfmt

        snap = tfmt.read_trace(args.trace).snapshot
        if snap is None:
            raise SystemExit(f"{args.trace}: no snapshot frame")
        P, T = snap.n_providers, snap.n_tasks
        cand_p_np, cand_c_np = native.fused_topk_candidates(
            tfmt._as_ns(snap.p_cols), tfmt._as_ns(snap.r_cols),
            CostWeights(*snap.weights), k=K, threads=args.threads,
        )
        print(
            f"# trace candidates built {time.time()-t0:.1f}s "
            f"(P={P} T={T})", file=sys.stderr, flush=True,
        )
    else:
        # uniform synthetic candidates (trace/synth.py — the shared home
        # of every synthetic population): execution evidence at shape
        from protocol_tpu.trace.synth import synth_uniform_candidates

        rng = np.random.default_rng(0)
        cand_p_np, cand_c_np = synth_uniform_candidates(rng, T, P, k=K)
        print(
            f"# synth built {time.time()-t0:.1f}s", file=sys.stderr,
            flush=True,
        )

    if args.engine == "native-mt":
        run_native_chain(args, cand_p_np, cand_c_np, P, T, EPS_END, emit)
        return

    cand_p = jnp.asarray(cand_p_np)
    cand_c = jnp.asarray(cand_c_np)
    del cand_p_np, cand_c_np

    mesh = make_mesh(8)
    t0 = time.time()
    res, price, retired = assign_auction_sparse_scaled_sharded(
        cand_p, cand_c, num_providers=P, mesh=mesh,
        eps_start=4.0, eps_end=EPS_END, max_iters_per_phase=512,
        frontier=8192, frontier_ladder=True, with_state=True,
    )
    cold_wall = time.time() - t0
    p4t = np.asarray(res.provider_for_task)
    emit({
        "step": 0, "kind": "cold", "engine": "jax",
        "wall_s": round(cold_wall, 1),
        "assigned": int((p4t >= 0).sum()),
        "retired": int(np.asarray(retired).sum()),
        "price_max": round(float(np.asarray(price).max()), 3),
    })

    n_churn = max(int(T * args.churn), 1)
    churn_rng = np.random.default_rng(7)
    for step in range(1, args.steps + 1):
        # churn a RANDOM slice each step (a fixed prefix would re-churn
        # the same tasks; random spread is the production shape). Churned
        # tasks lose their seat AND their retirement flag (they are "new"
        # work), mirroring the matcher's seed rebuild.
        idx = churn_rng.choice(T, size=n_churn, replace=False)
        p4t0 = jnp.asarray(p4t).at[idx].set(-1)
        retired = jnp.asarray(retired).at[idx].set(False)
        stats: dict = {}
        t0 = time.time()
        res, price, retired = assign_auction_sparse_warm_sharded(
            cand_p, cand_c, num_providers=P, mesh=mesh,
            price0=price, p4t0=p4t0, eps=EPS_END, max_iters=1024,
            frontier=8192, frontier_ladder=True,
            retired0=retired, with_state=True, stats_out=stats,
        )
        wall = time.time() - t0
        p4t = np.asarray(res.provider_for_task)
        pos = p4t[p4t >= 0]
        emit({
            "step": step, "kind": "warm", "engine": "jax",
            "wall_s": round(wall, 1),
            "assigned": int((p4t >= 0).sum()),
            "injective": bool(np.unique(pos).size == pos.size),
            "retired": int(np.asarray(retired).sum()),
            "price_max": round(float(np.asarray(price).max()), 3),
            "stall_exit": stats.get("stall_exit"),
        })


if __name__ == "__main__":
    main()
