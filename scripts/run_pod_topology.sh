#!/bin/bash
# Local pod-topology launcher: one OS process per service, wired by URLs +
# env secrets — the Helm-chart shape without kubernetes (the reference's
# docker-compose dev environment, docker-compose.yml). Ctrl-C stops all.
#
# Usage: scripts/run_pod_topology.sh [BASE_PORT] [STATE_DIR]
set -u
B=${1:-28500}
STATE=${2:-}
LEDGER=http://127.0.0.1:$((B+5))
DISC=http://127.0.0.1:$B
ORCH=http://127.0.0.1:$((B+1))
SCHED=127.0.0.1:$((B+6))
KV=http://127.0.0.1:$((B+7))
STATE_ARGS=()
[ -n "$STATE" ] && STATE_ARGS=(--state-dir "$STATE")

eval "$(python - <<'PYEOF'
from protocol_tpu.security import Wallet
for name in ("manager", "creator", "validator", "provider", "node"):
    w = Wallet.from_seed(f"pod-{name}".encode())
    print(f"{name.upper()}_KEY={w.private_key_hex()}")
    print(f"{name.upper()}_ADDR={w.address}")
PYEOF
)"

PIDS=()
trap 'kill "${PIDS[@]}" 2>/dev/null' EXIT INT TERM

python -m protocol_tpu.serve ledger-api --port $((B+5)) "${STATE_ARGS[@]}" &
PIDS+=($!)
for i in $(seq 1 60); do
  curl -sf "$LEDGER/health" > /dev/null 2>&1 && break
  sleep 0.5
done
curl -sf "$LEDGER/health" > /dev/null || { echo "ledger-api failed to start" >&2; exit 1; }

CLI="python -m protocol_tpu.cli --ledger $LEDGER --api-key admin"
if ! $CLI pool-info --pool-id 0 >/dev/null 2>&1; then
  $CLI mint --address "$PROVIDER_ADDR" --amount 100000 > /dev/null
  $CLI create-domain --name pods > /dev/null
  $CLI create-pool --domain-id 0 --creator "$CREATOR_ADDR" --manager "$MANAGER_ADDR" > /dev/null
  $CLI start-pool --pool-id 0 --caller "$CREATOR_ADDR" > /dev/null
  curl -s -X POST -H "Authorization: Bearer admin" -H "Content-Type: application/json" \
    -d "{\"address\": \"$VALIDATOR_ADDR\"}" "$LEDGER/ledger/write/grant_validator_role" > /dev/null
fi

python -m protocol_tpu.serve scheduler --address "$SCHED" &
PIDS+=($!)
KV_API_KEY=admin python -m protocol_tpu.serve kv-api --port $((B+7)) "${STATE_ARGS[@]}" &
PIDS+=($!)
ADMIN_API_KEY=admin python -m protocol_tpu.serve discovery \
  --ledger-url "$LEDGER" --pool-id 0 --port "$B" "${STATE_ARGS[@]}" &
PIDS+=($!)
sleep 2
MANAGER_KEY=$MANAGER_KEY ADMIN_API_KEY=admin DISCOVERY_URLS=$DISC \
  HEARTBEAT_URL=$ORCH LEDGER_API_KEY=admin KV_API_KEY=admin \
  python -m protocol_tpu.serve orchestrator --ledger-url "$LEDGER" --pool-id 0 \
  --port $((B+1)) --scheduler-backend "remote:$SCHED" \
  --mode api --kv-url "$KV" &
PIDS+=($!)
MANAGER_KEY=$MANAGER_KEY ADMIN_API_KEY=admin DISCOVERY_URLS=$DISC \
  HEARTBEAT_URL=$ORCH LEDGER_API_KEY=admin KV_API_KEY=admin \
  python -m protocol_tpu.serve orchestrator --ledger-url "$LEDGER" --pool-id 0 \
  --port $((B+8)) --scheduler-backend "remote:$SCHED" \
  --mode processor --kv-url "$KV" &
PIDS+=($!)
VALIDATOR_KEY=$VALIDATOR_KEY DISCOVERY_URLS=$DISC LEDGER_API_KEY=admin \
  python -m protocol_tpu.serve validator --ledger-url "$LEDGER" --pool-id 0 \
  --port $((B+4)) &
PIDS+=($!)
PROVIDER_KEY=$PROVIDER_KEY NODE_KEY=$NODE_KEY LEDGER_API_KEY=admin \
  python -m protocol_tpu.serve worker --ledger-url "$LEDGER" --pool-id 0 \
  --port $((B+10)) --discovery-urls "$DISC" --runtime subprocess \
  --socket-path /tmp/ptpu-pods-$B.sock &
PIDS+=($!)

sleep 10
$CLI whitelist-provider --provider "$PROVIDER_ADDR" > /dev/null 2>&1 || true

cat <<INFO
pod topology up:
  discovery       $DISC
  orchestrator    $ORCH         (api replica; processor health :$((B+8)))
  validator       http://127.0.0.1:$((B+4))
  ledger api      $LEDGER       (admin key: admin)
  kv store        $KV
  scheduler gRPC  $SCHED
try:
  python -m protocol_tpu.cli --orchestrator $ORCH --api-key admin \\
      create-task --name hello --image demo --cmd 'echo,hello'
INFO
wait
