"""Whole-program concurrency & contract analyzer (``python -m
scripts.analysis``).

Three interprocedural passes that go beyond the per-file lint engine
(``scripts.lints`` — which stays the home of the single-file AST rules):

  lock-order   call-graph-propagated lock-order graph over all of
               protocol_tpu/, checked for rank violations and cycles
               against the committed spec (lock_order.toml), plus the
               dropped-lock check on ``*_locked`` helpers. The runtime
               twin is protocol_tpu/utils/lockwitness.py
               (PROTOCOL_TPU_LOCK_WITNESS=1).
  protocol-sm  wire-v2 session lifecycle state-machine checker over the
               servicer handlers: ladder-recognizable refusals, decode
               hardening before any arena mutation, deadline before
               mutation, cursor/CRC advance and flush before ack.
  jax-purity   TPU-readiness pass over the jit closure (ops/, parallel/,
               the jax engine path): host syncs, ambient clock/RNG,
               Python control flow on traced values, float64-defaulting
               numpy constructors.

All passes emit the lint engine's Finding shape and share its SARIF
emitter; escapes are per-pass (``# lint: lock-order-ok`` /
``protocol-ok`` / ``purity-ok``) and audited for staleness by this
package's own runner, exactly like the lint engine audits its tokens.
"""

from scripts.analysis.spec import Spec, load_spec  # noqa: F401
from scripts.analysis import lockorder, protocolsm, purity  # noqa: F401

__all__ = ["Spec", "load_spec", "lockorder", "protocolsm", "purity"]
