"""JAX/TPU-readiness purity pass over the jit-compiled paths.

ROADMAP #5 makes the jax engine a first-class backend again, and the
whole premise of "TPU day is a flag flip" is that the jitted code is
trace-pure TODAY: no host syncs, no wall-clock or RNG inside a traced
region, no Python control flow on traced values, no silent float64
promotion sneaking in through numpy defaults. On CPU these bugs cost a
little; on a real TPU every one is either a compile error or a
device-to-host round-trip that erases the point of the hardware.

The pass finds every jit entry point — decorator form (``@jax.jit``,
``@partial(jax.jit, static_argnames=...)``) AND call form
(``gen = jax.jit(fn)`` / ``return jax.jit(shard_map(fn, ...),
static_argnames=...)``, the lru_cached sharded-builder idiom) — under
the jax roots (``ops/``, ``parallel/``, and the jax engine path in
``sched/tpu_backend.py``), closes over the call graph to every
reachable helper, and checks the closure:

  P1 host sync: ``.item()`` / ``.tolist()`` / ``.block_until_ready()``,
     and ``np.asarray``/``np.array`` applied to a traced value — each
     forces a device sync inside the traced region (TracerArray
     conversion error on TPU, silent round-trip under jit-of-CPU).

  P2 ambient impurity: ``time.*`` / ``random.*`` / ``np.random.*``
     calls inside the jit closure — traced once at compile time, then
     frozen: the jitted function replays the FIRST call's value forever
     (the classic "why is my jitter constant" bug).

  P3 Python control flow on traced values: an ``if``/``while`` whose
     test reads a traced parameter forces a concrete bool mid-trace.
     Static shape/dtype probing (``.shape``/``.ndim``/``.dtype``/
     ``.size``, ``is None`` checks, ``isinstance``) is legal and
     whitelisted — that is how kernels specialize per shape.

  P4 implicit dtype promotion: numpy array constructors without an
     explicit ``dtype=`` inside the closure (``np.zeros``/``np.ones``/
     ``np.full``/``np.arange``/``np.empty``/``np.linspace``) default to
     float64/int64 — mixed into a traced op they either promote the
     whole expression or silently truncate under x64-off, and the wire
     dtype contract is f32/i32.

Taint is deliberately coarse: inside a jit entry, every parameter not
named in ``static_argnames`` is traced; assignments propagate taint
lexically; helpers reached from jitted code treat ALL their parameters
as traced (a MAY analysis — the sound direction). Escape:
``# lint: purity-ok`` on the line, for values that are genuinely static
at trace time.
"""

from __future__ import annotations

import ast
from typing import Optional

from scripts.analysis.callgraph import Index, receiver_pattern
from scripts.lints.base import Finding, REPO

RULE = "jax-purity"
SUPPRESS = "purity-ok"

DEFAULT_ROOTS = (
    "protocol_tpu/ops",
    "protocol_tpu/parallel",
    "protocol_tpu/sched/tpu_backend.py",
)

HOST_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
NP_SYNC_FNS = {"asarray", "array"}
NP_PROMOTING_FNS = {
    "zeros", "ones", "full", "arange", "empty", "linspace",
}
STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "at"}

# transform layers a call-form jit may wrap around the actual kernel:
# jax.jit(shard_map(fn, mesh=...)) / jax.jit(partial(fn, ...)) — the
# traced body is the innermost named function
JIT_WRAPPERS = {"shard_map", "partial", "vmap", "pmap", "checkpoint", "remat"}


def _jit_static_argnames(dec: ast.AST) -> Optional[tuple]:
    """If ``dec`` is a jit decorator, return its static_argnames tuple
    (possibly empty); else None."""
    # @jax.jit / @jit
    if isinstance(dec, ast.Attribute) and dec.attr == "jit":
        return ()
    if isinstance(dec, ast.Name) and dec.id == "jit":
        return ()
    if isinstance(dec, ast.Call):
        fn = dec.func
        # @partial(jax.jit, static_argnames=(...)) / @jax.jit(...)
        is_partial = (
            isinstance(fn, ast.Name) and fn.id == "partial"
            or isinstance(fn, ast.Attribute) and fn.attr == "partial"
        )
        if is_partial:
            if not dec.args or _jit_static_argnames(dec.args[0]) is None:
                return None
        elif _jit_static_argnames(fn) is None:
            return None
        names: list = []
        for kw in dec.keywords:
            if kw.arg in ("static_argnames", "static_argnums") and (
                isinstance(kw.value, (ast.Tuple, ast.List))
            ):
                names.extend(
                    e.value for e in kw.value.elts
                    if isinstance(e, ast.Constant)
                )
            elif kw.arg in ("static_argnames",) and isinstance(
                kw.value, ast.Constant
            ):
                names.append(kw.value.value)
        return tuple(names)
    return None


def _callform_target_name(call: ast.Call) -> Optional[str]:
    """The function NAME a call-form jit wraps: ``jax.jit(fn)`` -> "fn",
    unwrapping transform layers (``jax.jit(shard_map(fn, mesh=...))``,
    ``jax.jit(partial(fn, ...))``). None for the decorator-factory shape
    (``partial(jax.jit, ...)`` / ``jax.jit(static_argnames=...)``) — no
    wrapped function rides in the positional slot there."""
    if not call.args:
        return None
    inner = call.args[0]
    while isinstance(inner, ast.Call):
        f = inner.func
        name = (
            f.attr if isinstance(f, ast.Attribute)
            else f.id if isinstance(f, ast.Name) else None
        )
        if name not in JIT_WRAPPERS or not inner.args:
            return None
        inner = inner.args[0]
    return inner.id if isinstance(inner, ast.Name) else None


class _Taint:
    """Lexical taint set for one function body."""

    def __init__(self, fn: ast.AST, static_names: set):
        self.tainted: set[str] = set()
        args = fn.args
        for a in (
            list(args.posonlyargs) + list(args.args)
            + list(args.kwonlyargs)
        ):
            if a.arg not in static_names and a.arg not in ("self", "cls"):
                self.tainted.add(a.arg)

    def expr_tainted(self, expr: ast.AST) -> bool:
        """A tainted Name taints the expression UNLESS every use goes
        through a static probe: ``x.shape[0]`` / ``x.ndim`` / ``x.dtype``
        are trace-time constants even when ``x`` is traced — that is the
        legal shape-specialization idiom, not a host sync."""
        for sub in ast.walk(expr):
            if not (
                isinstance(sub, ast.Name) and sub.id in self.tainted
            ):
                continue
            if not _through_static_attr(sub):
                return True
        return False

    def assign(self, node: ast.AST) -> None:
        targets = []
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets, value = [node.target], node.value
        else:
            return
        if value is None:
            return
        names = [
            t.id for tgt in targets for t in ast.walk(tgt)
            if isinstance(t, ast.Name)
        ]
        if self.expr_tainted(value):
            self.tainted.update(names)
        else:
            # retaint-kill: a name rebound to a pure value is clean again
            for n in names:
                self.tainted.discard(n)


def _through_static_attr(name: ast.Name) -> bool:
    """Does this Name use flow through a ``.shape``/``.ndim``/... probe
    (anywhere up its attribute chain)?"""
    node: ast.AST = name
    parent = getattr(node, "_pp_parent", None)
    while isinstance(parent, (ast.Attribute, ast.Subscript)):
        if isinstance(parent, ast.Attribute) and (
            parent.attr in STATIC_ATTRS
        ):
            return True
        node, parent = parent, getattr(parent, "_pp_parent", None)
    return False


def _static_only_test(test: ast.AST, taint: _Taint) -> bool:
    """True when every tainted name in the test is reached only through
    static probes (shape/ndim/dtype/size), ``is [not] None``, or
    isinstance — the legal specialization idioms."""
    for sub in ast.walk(test):
        if not isinstance(sub, ast.Name) or sub.id not in taint.tainted:
            continue
        if _through_static_attr(sub):
            continue
        parent = getattr(sub, "_pp_parent", None)
        ok = False
        if isinstance(parent, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot))
            for op in parent.ops
        ):
            ok = True
        elif isinstance(parent, ast.Call) and (
            isinstance(parent.func, ast.Name)
            and parent.func.id in ("isinstance", "len")
        ):
            ok = True
        if not ok:
            return False
    return True


def _link_parents(root: ast.AST) -> None:
    for node in ast.walk(root):
        for child in ast.iter_child_nodes(node):
            child._pp_parent = node  # type: ignore[attr-defined]


class PurityChecker:
    def __init__(self, roots=DEFAULT_ROOTS, index: Optional[Index] = None):
        # purity resolves calls structurally; the lock spec's receiver
        # tables are irrelevant here, so the index may omit the spec
        self.index = (
            index if index is not None else Index.build(roots)
        )
        self.findings: list[Finding] = []
        self.consumed: set = set()  # (rel, line) escapes that fired
        self._lines: dict[str, list] = {}

    # ---------------- jit closure ----------------

    def jit_entries(self) -> dict[str, tuple]:
        """qname -> static_argnames for every jit entry: decorator form
        plus call form (``gen = jax.jit(fn, ...)`` assigned or returned
        anywhere under the roots — the lru_cached sharded-builder idiom
        the decorator scan cannot see)."""
        out = {}
        for qname, info in self.index.functions.items():
            for dec in getattr(info.node, "decorator_list", ()):
                names = _jit_static_argnames(dec)
                if names is not None:
                    out[qname] = names
                    break
        for rel, tree in self.index.trees.items():
            for node in ast.walk(tree):
                value = None
                if isinstance(
                    node, (ast.Assign, ast.AnnAssign, ast.Return)
                ):
                    value = node.value
                if not isinstance(value, ast.Call):
                    continue
                names = _jit_static_argnames(value)
                if names is None:
                    continue
                target = _callform_target_name(value)
                if target is None:
                    continue
                for qname in self._resolve_in_file(rel, target):
                    out.setdefault(qname, names)
        return out

    def _resolve_in_file(self, rel: str, name: str) -> list:
        """Resolve the bare function name at a call-form jit site: every
        same-file definition (top level or nested — builders jit their
        local closures; multiple hits is the sound MAY direction), else
        one import edge into another indexed module."""
        local = [
            q for q in self.index.by_name.get(name, ())
            if self.index.functions[q].rel == rel
        ]
        if local:
            return local
        imp = self.index.imports.get(rel, {}).get(name)
        if imp is not None:
            q = self.index.modules.get(imp[0], {}).get(imp[1])
            if q:
                return [q]
        return []

    def closure(self, entries) -> set[str]:
        seen = set(entries)
        frontier = list(entries)
        while frontier:
            qname = frontier.pop()
            info = self.index.functions[qname]
            for call in ast.walk(info.node):
                if not isinstance(call, ast.Call):
                    continue
                for callee in self.index.resolve_call(call, info):
                    if callee not in seen:
                        seen.add(callee)
                        frontier.append(callee)
        return seen

    # ---------------- checks ----------------

    def run(self) -> list[Finding]:
        entries = self.jit_entries()
        reach = self.closure(entries)
        for qname in sorted(reach):
            info = self.index.functions[qname]
            static_names = set(entries.get(qname, ()))
            self._check_function(info, static_names)
        return self.findings

    def _check_function(self, info, static_names: set) -> None:
        fn = info.node
        _link_parents(fn)
        taint = _Taint(fn, static_names)
        self._walk_block(info, fn.body, taint)

    def _walk_block(self, info, stmts, taint: _Taint) -> None:
        for st in stmts:
            if isinstance(
                st, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                # nested defs (scan/while bodies) inherit the taint of
                # their free variables; conservatively, their params are
                # traced too (they receive carry/batch values)
                inner = _Taint(st, set())
                inner.tainted |= taint.tainted
                self._walk_block(info, st.body, inner)
                continue
            taint.assign(st)
            if isinstance(st, (ast.If, ast.While)):
                if taint.expr_tainted(st.test) and not _static_only_test(
                    st.test, taint
                ):
                    self._find(
                        info, st,
                        "Python control flow on a traced value — "
                        "forces a concrete bool mid-trace; use "
                        "lax.cond/select or jnp.where",
                    )
                self._check_stmt_calls(info, st.test, taint)
                self._walk_block(info, st.body, taint)
                self._walk_block(info, st.orelse, taint)
                continue
            if isinstance(st, (ast.For, ast.AsyncFor)):
                # loop variables of a tainted iterable are traced
                self._check_stmt_calls(info, st.iter, taint)
                if taint.expr_tainted(st.iter):
                    taint.tainted.update(
                        n.id for n in ast.walk(st.target)
                        if isinstance(n, ast.Name)
                    )
                self._walk_block(info, st.body, taint)
                self._walk_block(info, st.orelse, taint)
                continue
            if isinstance(st, (ast.With, ast.AsyncWith)):
                for item in st.items:
                    self._check_stmt_calls(
                        info, item.context_expr, taint
                    )
                self._walk_block(info, st.body, taint)
                continue
            if isinstance(st, ast.Try):
                self._walk_block(info, st.body, taint)
                for h in st.handlers:
                    self._walk_block(info, h.body, taint)
                self._walk_block(info, st.orelse, taint)
                self._walk_block(info, st.finalbody, taint)
                continue
            self._check_stmt_calls(info, st, taint)

    def _check_stmt_calls(self, info, node: ast.AST, taint) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                self._check_call(info, sub, taint)

    def _check_call(self, info, call: ast.Call, taint: _Taint) -> None:
        fn = call.func
        if isinstance(fn, ast.Attribute):
            recv = receiver_pattern(fn.value)
            root = recv.split(".", 1)[0]
            # P1: device->host syncs
            if fn.attr in HOST_SYNC_METHODS:
                self._find(
                    info, call,
                    f".{fn.attr}() inside a jit-reachable path — "
                    "device-to-host sync (TracerArray conversion on "
                    "TPU)",
                )
                return
            if root in ("np", "numpy"):
                if fn.attr in NP_SYNC_FNS and any(
                    taint.expr_tainted(a) for a in call.args
                ):
                    self._find(
                        info, call,
                        f"np.{fn.attr}() on a traced value inside jit "
                        "— host materialization of a tracer",
                    )
                    return
                # P2: np.random.*
                if recv.endswith(".random"):
                    self._find(
                        info, call,
                        "np.random inside a jit-reachable path — "
                        "traced once, frozen forever; thread "
                        "jax.random keys instead",
                    )
                    return
                # P4: float64-defaulting constructors
                if fn.attr in NP_PROMOTING_FNS and not any(
                    kw.arg == "dtype" for kw in call.keywords
                ) and len(call.args) < _dtype_positional(fn.attr):
                    self._find(
                        info, call,
                        f"np.{fn.attr}() without dtype= inside a "
                        "jit-reachable path — float64/int64 default "
                        "promotes or truncates against the f32/i32 "
                        "wire contract",
                    )
                    return
            # P2: wall clock / random module
            if root == "time":
                self._find(
                    info, call,
                    "wall-clock read inside a jit-reachable path — "
                    "traced once at compile time, frozen thereafter",
                )
                return
            if root == "random":
                self._find(
                    info, call,
                    "random module inside a jit-reachable path — "
                    "traced once at compile time, frozen thereafter",
                )
                return

    # ---------------- reporting ----------------

    def _find(self, info, node, msg: str) -> None:
        line = getattr(node, "lineno", 0)
        lines = self._file_lines(info.rel)
        if lines and 1 <= line <= len(lines):
            if f"lint: {SUPPRESS}" in lines[line - 1]:
                self.consumed.add((info.rel, line))
                return
        self.findings.append(Finding(RULE, info.rel, line, msg))

    def _file_lines(self, rel: str):
        if rel not in self._lines:
            try:
                self._lines[rel] = (REPO / rel).read_text().splitlines()
            except OSError:
                self._lines[rel] = []
        return self._lines[rel]


def _dtype_positional(ctor: str) -> int:
    """Positional arity at which dtype would appear for each numpy
    constructor (np.zeros((n,), np.float32) passes dtype positionally)."""
    return {
        "zeros": 2, "ones": 2, "empty": 2, "full": 3,
        "arange": 4, "linspace": 7,
    }.get(ctor, 2)


def run(roots=DEFAULT_ROOTS, index=None) -> list[Finding]:
    return PurityChecker(roots, index=index).run()
