"""CLI for the whole-program analyzer: ``python -m scripts.analysis``.

Runs all five passes (or a ``--pass`` subset), audits this engine's
escape tokens for staleness, prints findings in the lint engine's
``path:line: [rule] message`` shape, and exits 1 on any finding — the
same fail-the-build discipline as ``python -m scripts.lints``.
"""

from __future__ import annotations

import argparse
import re
import sys

from scripts.analysis import lockorder, protocolsm, purity, spmd, staging
from scripts.analysis.spec import load_spec
from scripts.lints.base import REPO, Finding

_PASSES = (
    "lock-order", "protocol-sm", "jax-purity", "jax-retrace",
    "spmd-contract",
)

_TOKEN_RE = re.compile(r"#\s*lint:\s*([A-Za-z0-9_-]+)")


def _audit_own_escapes(files, token: str, consumed: set) -> list[Finding]:
    """Stale-escape audit for one pass: every annotation of this pass's
    token in its scanned files must have suppressed a finding."""
    out: list[Finding] = []
    for rel in sorted(files):
        try:
            lines = (REPO / rel).read_text().splitlines()
        except OSError:
            continue
        for lineno, text in enumerate(lines, 1):
            m = _TOKEN_RE.search(text)
            if m is None or m.group(1) != token:
                continue
            if (rel, lineno) not in consumed:
                out.append(Finding(
                    "stale-escape", rel, lineno,
                    f"escape '# lint: {token}' suppresses no finding "
                    "— remove it (suppressions must not rot)",
                ))
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m scripts.analysis",
        description="whole-program concurrency & contract analyzer "
                    "(lock-order / protocol-sm / jax-purity / "
                    "jax-retrace / spmd-contract)",
    )
    ap.add_argument("--pass", dest="passes", action="append",
                    choices=_PASSES, default=None,
                    help="run only this pass (repeatable; default: all)")
    ap.add_argument("--graph", action="store_true",
                    help="print the observed lock-order graph and exit")
    ap.add_argument("--sarif", default=None, metavar="OUT.json",
                    help="also write findings as SARIF 2.1.0 (shared "
                         "emitter with scripts.lints)")
    args = ap.parse_args(argv)
    passes = tuple(args.passes) if args.passes else _PASSES

    spec = load_spec()
    findings: list[Finding] = []

    if "lock-order" in passes or args.graph:
        an = lockorder.LockOrderAnalyzer(spec=spec)
        findings.extend(an.run())
        if args.graph:
            print("observed lock-order graph (held -> acquired):")
            for line in an.graph_lines():
                print("  " + line)
            return 0
        files = {
            info.rel for info in an.index.functions.values()
        }
        findings.extend(_audit_own_escapes(
            files, lockorder.SUPPRESS, an.consumed
        ))

    if "protocol-sm" in passes:
        ck = protocolsm.ProtocolChecker(spec=spec)
        findings.extend(ck.run())
        findings.extend(_audit_own_escapes(
            set(protocolsm.DEFAULT_ROOTS), protocolsm.SUPPRESS,
            ck.consumed,
        ))

    # the three jax passes share one Index over the same roots
    jax_index = None
    if {"jax-purity", "jax-retrace", "spmd-contract"} & set(passes):
        from scripts.analysis.callgraph import Index

        jax_index = Index.build(purity.DEFAULT_ROOTS)

    if "jax-purity" in passes:
        pc = purity.PurityChecker(index=jax_index)
        findings.extend(pc.run())
        files = {info.rel for info in pc.index.functions.values()}
        findings.extend(_audit_own_escapes(
            files, purity.SUPPRESS, pc.consumed
        ))

    if "jax-retrace" in passes:
        st = staging.StagingChecker(index=jax_index)
        findings.extend(st.run())
        files = {info.rel for info in st.index.functions.values()}
        findings.extend(_audit_own_escapes(
            files, staging.SUPPRESS, st.consumed
        ))

    if "spmd-contract" in passes:
        sm = spmd.SpmdChecker(index=jax_index)
        findings.extend(sm.run())
        files = {info.rel for info in sm.index.functions.values()}
        findings.extend(_audit_own_escapes(
            files, spmd.SUPPRESS, sm.consumed
        ))

    for f in findings:
        print(f)
    if args.sarif:
        from scripts.lints.sarif import write_sarif

        write_sarif(
            args.sarif, findings, "scripts.analysis",
            rule_help={
                "lock-order": "lock acquisition violates the committed "
                              "rank order (lock_order.toml)",
                "protocol-sm": "servicer handler diverges from the "
                               "wire-v2 session lifecycle model",
                "jax-purity": "jit-reachable code is not trace-pure "
                              "(host sync / ambient state / promotion)",
                "jax-retrace": "jit staging hazard: static-argname "
                               "miss, mutable capture, or polymorphic "
                               "compile key (recompile per tick)",
                "spmd-contract": "shard_map site violates the committed "
                                 "mesh/axis/D-invariance contract "
                                 "(spmd_spec.toml)",
                "stale-escape": "escape annotation suppresses nothing",
            },
        )
        print(f"sarif written: {args.sarif} ({len(findings)} finding(s))")
    if not findings:
        print(f"analysis clean ({', '.join(passes)}) over protocol_tpu")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
