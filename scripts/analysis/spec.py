"""Committed-spec loader for the whole-program analyzer.

``lock_order.toml`` is the single source of truth three consumers share:

  * the static lock-order pass (``scripts/analysis/lockorder.py``) —
    classifies every lock expression into a domain and checks the
    call-graph-propagated acquisition edges against the rank order;
  * the runtime witness (``protocol_tpu/utils/lockwitness.py``) —
    asserts the same rank order live under the race/chaos suites;
  * the protocol checker (``scripts/analysis/protocolsm.py``) — reads
    the ladder-marker table from the ``[protocol]`` section.

This container pins Python 3.10 (no stdlib ``tomllib``), so the loader
carries a minimal TOML-subset parser: ``[section]`` headers and
``key = value`` lines where value is an int, a float, a bool, a quoted
string, or a flat array of quoted strings — exactly the shapes the spec
uses, nothing more. When the interpreter has ``tomllib`` it is used
instead, so the subset parser can never drift from real TOML silently.
"""

from __future__ import annotations

import dataclasses
import os
import re
from typing import Optional

_KEY = r'(?:"(?P<qkey>[^"]+)"|(?P<key>[A-Za-z0-9_.\-]+))'
_LINE = re.compile(rf"^\s*{_KEY}\s*=\s*(?P<value>.+?)\s*$")
_SECTION = re.compile(r"^\s*\[(?P<name>[A-Za-z0-9_.\-]+)\]\s*$")


def _parse_value(raw: str):
    raw = raw.strip()
    if raw.startswith("["):
        if not raw.endswith("]"):
            raise ValueError(f"unterminated array: {raw!r}")
        body = raw[1:-1].strip()
        if not body:
            return []
        items = []
        for part in body.split(","):
            part = part.strip()
            if not part:
                continue
            if not (part.startswith('"') and part.endswith('"')):
                raise ValueError(f"array items must be strings: {part!r}")
            items.append(part[1:-1])
        return items
    if raw.startswith('"'):
        if not (raw.endswith('"') and len(raw) >= 2):
            raise ValueError(f"unterminated string: {raw!r}")
        return raw[1:-1]
    if raw in ("true", "false"):
        return raw == "true"
    try:
        return int(raw)
    except ValueError:
        return float(raw)


def parse_toml_subset(text: str) -> dict:
    out: dict = {}
    section: dict = out
    pending: Optional[str] = None  # multi-line array accumulator
    for lineno, line in enumerate(text.splitlines(), 1):
        # strip full-line and trailing comments (the spec never puts '#'
        # inside strings, so a bare split is sound for this subset)
        stripped = line.split("#", 1)[0].rstrip()
        if pending is not None:
            pending += " " + stripped.strip()
            if stripped.strip().endswith("]"):
                m = _LINE.match(pending)
                if m is None:
                    raise ValueError(
                        f"line {lineno}: cannot parse array {pending!r}"
                    )
                key = m.group("qkey") or m.group("key")
                section[key] = _parse_value(m.group("value"))
                pending = None
            continue
        if not stripped.strip():
            continue
        m = _SECTION.match(stripped)
        if m:
            section = out.setdefault(m.group("name"), {})
            continue
        if stripped.count("[") > stripped.count("]") and "=" in stripped:
            pending = stripped.strip()
            continue
        m = _LINE.match(stripped)
        if m is None:
            raise ValueError(f"line {lineno}: cannot parse {line!r}")
        key = m.group("qkey") or m.group("key")
        section[key] = _parse_value(m.group("value"))
    if pending is not None:
        raise ValueError(f"unterminated multi-line array: {pending!r}")
    return out


def _load_toml(path: str) -> dict:
    with open(path, "rb") as fh:
        data = fh.read()
    try:
        import tomllib  # Python >= 3.11

        return tomllib.loads(data.decode())
    except ImportError:
        return parse_toml_subset(data.decode())


@dataclasses.dataclass(frozen=True)
class Spec:
    """The parsed lock-order spec."""

    ranks: dict  # domain -> int rank (strictly ascending acquisition)
    reentrant: tuple  # domains with RLock semantics
    classify_attr: dict  # lock attribute name -> domain
    classify_class: dict  # "ClassName.attr" -> domain
    receivers: dict  # receiver expr pattern -> class name
    callbacks: dict  # "receiver.attr" call -> list of concrete functions
    ladder_markers: tuple  # substrings the client ladder recognizes
    skip_files: tuple  # repo-relative files the lock pass never scans

    def domain_of(
        self, attr: str, class_name: Optional[str] = None
    ) -> Optional[str]:
        if class_name is not None:
            dom = self.classify_class.get(f"{class_name}.{attr}")
            if dom is not None:
                return dom
        return self.classify_attr.get(attr)


def load_spec(path: Optional[str] = None) -> Spec:
    if path is None:
        path = os.path.join(os.path.dirname(__file__), "lock_order.toml")
    doc = _load_toml(path)
    ranks = {k: int(v) for k, v in doc.get("domains", {}).items()}
    unknown = [
        d for d in doc.get("reentrant", {}).get("domains", [])
        if d not in ranks
    ]
    if unknown:
        raise ValueError(f"reentrant domains missing ranks: {unknown}")
    for table in ("classify", "classify_class"):
        for key, dom in doc.get(table, {}).items():
            if dom not in ranks:
                raise ValueError(
                    f"[{table}] {key!r} maps to unranked domain {dom!r}"
                )
    return Spec(
        ranks=ranks,
        reentrant=tuple(doc.get("reentrant", {}).get("domains", [])),
        classify_attr=dict(doc.get("classify", {})),
        classify_class=dict(doc.get("classify_class", {})),
        receivers=dict(doc.get("receivers", {})),
        callbacks={
            k: (v if isinstance(v, list) else [v])
            for k, v in doc.get("callbacks", {}).items()
        },
        ladder_markers=tuple(
            doc.get("protocol", {}).get("ladder_markers", [])
        ),
        skip_files=tuple(doc.get("scan", {}).get("skip_files", [])),
    )
