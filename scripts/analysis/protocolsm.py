"""Session-protocol state-machine checker for the wire-v2 lifecycle.

The wire-v2 session protocol is a state machine the servicer implements
by hand (scheduler_grpc.py) and the client ladder dispatches on by
error-string markers. Nothing machine-checks that the handler code still
implements the model — this pass does, against the committed lifecycle
below and the ``[protocol]`` marker table in ``lock_order.toml``.

The model (states x transitions; OUTCOME names match the seam counters)::

    CLOSED  --OpenSession ok-->                       WARM  (tick 0 ackd)
    CLOSED  --OpenSession refused (capability)-->     UNARY (ladder demoted)
    CLOSED  --OpenSession refused (throttle/drain)--> CLOSED (retry/degrade)
    WARM    --AssignDelta tick==cursor+1 ok-->        WARM  (cursor+1, ackd)
    WARM    --AssignDelta tick==cursor, crc match-->  WARM  (replayed ack)
    WARM    --AssignDelta refused (throttle)-->       WARM  (retry in place)
    WARM    --AssignDelta refused (mismatch/evict)--> CLOSED (re-open)
    WARM    --evict/ttl/drop-->                       CLOSED
    WARM    --crash + checkpoint restore-->           WARM  (cursor kept)

What the checker enforces on every handler function (a function
returning a ``pb.*Response`` carrying ``ok=``/``session_ok=``):

  R1 ladder-recognizable refusals: every ``ok=False`` return's error
     text must carry one of the committed ladder markers — the client
     dispatches on these substrings; an unrecognized refusal is treated
     as transient forever (the silent-retry-loop bug). Non-literal
     errors are allowed only in decode-hardening except-blocks (the
     transient rung by design) or when bound from a store lookup's
     refusal reason (whose strings the store owns).

  R2 decode-hardening precedes arena mutation: every decode call
     (``assemble_snapshot``/``decode_*_v2``/``unblob``) must sit inside
     a try that catches ``ValueError``, and every decode must lexically
     precede the first session mutation (``apply_delta``/``solve``/
     ``put``) — a handler that moves state before the frame is proven
     well-formed can be desynced by one corrupt byte.

  R3 deadline before mutation: a handler that mutates session state and
     consults the RPC deadline must do so BEFORE the first mutation —
     aborting after ``apply_delta`` but before the ack lets the client's
     retry double-apply the tick (the exact PR 9 review catch).

  R4 cursor/CRC advance before ack: on the delta ack path, the tick
     cursor advance and the retransmit-CRC store must precede the
     ``session_ok=True`` return (and the checkpoint flush, when
     configured, sits between them) — an ack before the cursor moved
     breaks exactly-once delta application across crash/retry. On the
     open path, the session must be published (``put``) before the ack.

Escape: ``# lint: protocol-ok`` on the offending line.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Optional

from scripts.analysis.spec import Spec, load_spec
from scripts.lints.base import Finding, Source, iter_files

RULE = "protocol-sm"
SUPPRESS = "protocol-ok"

# servicer files the checker scans by default (fixtures are passed
# explicitly by the tests)
DEFAULT_ROOTS = ("protocol_tpu/services/scheduler_grpc.py",)

DECODE_FNS = {
    "assemble_snapshot", "decode_providers_v2", "decode_requirements_v2",
    "unblob",
}
MUTATION_FNS = {"apply_delta", "solve", "put", "apply", "apply_burst"}
# "apply"/"apply_burst" are the STREAM engine's event mutations
# (session.stream.apply routes an event-typed delta into the arena):
# the deadline/decode-before-mutation rules cover the event surface
# with the same teeth as the batch path
DEADLINE_FNS = {"_check_deadline"}
FLUSH_FNS = {"flush_locked"}
CURSOR_ATTRS = {"tick"}
CRC_ATTRS = {"last_delta_crc"}


@dataclasses.dataclass
class _Return:
    node: ast.Return
    ok: bool
    replayed: bool
    error: ast.AST  # the error= keyword value (None if absent)


def _literal_text(node: ast.AST) -> Optional[str]:
    """Best-effort constant text of an error expression: plain strings,
    f-strings (all constant parts), and +-concatenation."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts = [
            v.value for v in node.values
            if isinstance(v, ast.Constant) and isinstance(v.value, str)
        ]
        return "".join(parts) if parts else None
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        left = _literal_text(node.left)
        right = _literal_text(node.right)
        if left is not None or right is not None:
            return (left or "") + (right or "")
    return None


def _call_name(call: ast.Call) -> str:
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return ""


class _HandlerScan(ast.NodeVisitor):
    """Collect the protocol events of one handler function, in lexical
    (== straight-line execution) order."""

    def __init__(self, fn: ast.AST):
        self.fn = fn
        self.returns: list[_Return] = []
        self.decodes: list = []  # (node, hardened: bool)
        self.mutations: list = []
        self.deadline_checks: list = []
        self.flushes: list = []
        self.cursor_advances: list = []
        self.crc_stores: list = []
        self.puts: list = []
        self._try_depth: list = []  # stack of "catches ValueError" flags
        for st in fn.body:
            self.visit(st)

    # -- structure --

    def visit_Try(self, node: ast.Try) -> None:
        catches = any(
            h.type is None
            or ("ValueError" in ast.unparse(h.type))
            or ("Exception" in ast.unparse(h.type))
            for h in node.handlers
        )
        self._try_depth.append(catches)
        for st in node.body:
            self.visit(st)
        self._try_depth.pop()
        for h in node.handlers:
            for st in h.body:
                self.visit(st)
        for st in node.orelse + node.finalbody:
            self.visit(st)

    def visit_FunctionDef(self, node) -> None:
        pass  # nested defs are their own handlers (or not handlers)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node) -> None:
        pass

    # -- events --

    def visit_Call(self, node: ast.Call) -> None:
        name = _call_name(node)
        if name in DECODE_FNS:
            self.decodes.append((node, any(self._try_depth)))
        elif name in MUTATION_FNS:
            if name == "put":
                self.puts.append(node)
            self.mutations.append(node)
        elif name in DEADLINE_FNS or "deadline" in name:
            self.deadline_checks.append(node)
        elif name in FLUSH_FNS:
            self.flushes.append(node)
        self.generic_visit(node)

    def _attr_store(self, target: ast.AST, node: ast.AST) -> None:
        if not isinstance(target, ast.Attribute):
            return
        if target.attr in CURSOR_ATTRS:
            self.cursor_advances.append(node)
        elif target.attr in CRC_ATTRS:
            self.crc_stores.append(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._attr_store(t, node)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._attr_store(node.target, node)
        self.generic_visit(node)

    def visit_Return(self, node: ast.Return) -> None:
        val = node.value
        if isinstance(val, ast.Call):
            kws = {
                k.arg: k.value for k in val.keywords if k.arg is not None
            }
            ok_kw = kws.get("ok", kws.get("session_ok"))
            if ok_kw is not None and isinstance(ok_kw, ast.Constant):
                replayed = isinstance(
                    kws.get("replayed"), ast.Constant
                ) and bool(kws["replayed"].value)
                self.returns.append(_Return(
                    node, bool(ok_kw.value), replayed, kws.get("error")
                ))
        self.generic_visit(node)


def _reason_names(fn: ast.AST) -> set[str]:
    """Names tuple-bound from a ``.get(...)`` store lookup — the store
    owns those refusal strings (R1's third allowed shape)."""
    out: set[str] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign) or not isinstance(
            node.value, ast.Call
        ):
            continue
        if _call_name(node.value) != "get":
            continue
        for t in node.targets:
            if isinstance(t, ast.Tuple):
                out |= {
                    e.id for e in t.elts if isinstance(e, ast.Name)
                }
    return out


class ProtocolChecker:
    def __init__(self, roots=DEFAULT_ROOTS, spec: Optional[Spec] = None):
        self.roots = roots
        self.spec = spec if spec is not None else load_spec()
        self.findings: list[Finding] = []
        self.consumed: set = set()  # (rel, line) escapes that fired

    def run(self) -> list[Finding]:
        for path in iter_files(self.roots):
            try:
                src = Source(path)
            except SyntaxError:
                continue  # the lint engine owns syntax reporting
            self.check_source(src)
        return self.findings

    # ---------------- per-file ----------------

    def check_source(self, src: Source) -> None:
        for node in ast.walk(src.tree):
            if not isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue
            scan = _HandlerScan(node)
            if not scan.returns:
                continue  # not a protocol handler
            self._check_handler(src, node, scan)

    def _check_handler(self, src: Source, fn, scan: _HandlerScan) -> None:
        markers = self.spec.ladder_markers
        reason_ok = _reason_names(fn)

        # R1: ladder-recognizable refusal text
        for ret in scan.returns:
            if ret.ok:
                continue
            text = _literal_text(ret.error)
            if text is not None:
                if not any(m in text for m in markers):
                    self._find(
                        src, ret.node,
                        f"refusal error {text[:48]!r} carries no "
                        "ladder marker — the client will treat it as "
                        "transient forever (markers: "
                        f"{', '.join(markers[:3])}, ...)",
                    )
                continue
            if ret.error is None:
                self._find(
                    src, ret.node,
                    "refusal with no error text — the ladder cannot "
                    "classify it",
                )
                continue
            in_handler = self._inside_except(src, ret.node)
            is_reason = (
                isinstance(ret.error, ast.Name)
                and ret.error.id in reason_ok
            )
            if not in_handler and not is_reason:
                self._find(
                    src, ret.node,
                    "refusal error is computed "
                    f"({ast.unparse(ret.error)!r}) outside a decode "
                    "except-block and not a store-lookup reason — "
                    "the ladder cannot rely on its markers",
                )

        # R2: decode hardening + decode-before-mutation
        first_mut = min(
            (m.lineno for m in scan.mutations), default=None
        )
        for node, hardened in scan.decodes:
            if not hardened:
                self._find(
                    src, node,
                    f"decode call {_call_name(node)}() outside a "
                    "ValueError-hardened try — a corrupt frame becomes "
                    "an unhandled exception mid-handler",
                )
            if first_mut is not None and node.lineno > first_mut:
                self._find(
                    src, node,
                    f"decode call {_call_name(node)}() after session "
                    f"state moved (line {first_mut}) — hardening must "
                    "precede any mutation",
                )

        # R3: deadline before mutation
        if scan.mutations and scan.deadline_checks:
            for node in scan.deadline_checks:
                if node.lineno > first_mut:
                    self._find(
                        src, node,
                        "deadline honored AFTER session state moved "
                        f"(first mutation line {first_mut}) — an abort "
                        "here lets the client's retry double-apply "
                        "the tick",
                    )

        # R4: cursor/CRC advance (and flush/publish) before ack
        acks = [
            r for r in scan.returns if r.ok and not r.replayed
        ]
        for ret in acks:
            line = ret.node.lineno
            if scan.crc_stores and not any(
                n.lineno < line for n in scan.crc_stores
            ):
                self._find(
                    src, ret.node,
                    "ack before the retransmit-CRC store — a replayed "
                    "delta would re-apply instead of deduping",
                )
            if scan.cursor_advances and scan.crc_stores and not any(
                n.lineno < line for n in scan.cursor_advances
            ):
                self._find(
                    src, ret.node,
                    "ack before the tick-cursor advance — the client "
                    "and server cursors diverge on the next delta",
                )
            for fl in scan.flushes:
                if fl.lineno > line:
                    self._find(
                        src, fl,
                        "checkpoint flush AFTER the ack return — a "
                        "crash between them loses an acknowledged tick "
                        "(flush-before-ack is the recovery contract)",
                    )
            if scan.puts and scan.decodes and not any(
                p.lineno < line for p in scan.puts
            ):
                self._find(
                    src, ret.node,
                    "ack before the session is published to the store "
                    "— the first delta would refuse with unknown "
                    "session",
                )

    # ---------------- helpers ----------------

    @staticmethod
    def _inside_except(src: Source, node: ast.AST) -> bool:
        return any(
            isinstance(anc, ast.ExceptHandler)
            for anc in src.ancestors(node)
        )

    def _find(self, src: Source, node, msg: str) -> None:
        line = getattr(node, "lineno", 0)
        if 1 <= line <= len(src.lines):
            if f"lint: {SUPPRESS}" in src.lines[line - 1]:
                self.consumed.add((src.rel, line))
                return
        self.findings.append(Finding(RULE, src.rel, line, msg))


def run(roots=DEFAULT_ROOTS, spec=None) -> list[Finding]:
    return ProtocolChecker(roots, spec=spec).run()
