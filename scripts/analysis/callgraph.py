"""Whole-program function index + best-effort call resolution.

The per-file lints (``scripts/lints``) are deliberately local: one AST,
one rule, no knowledge of who calls whom. The analyzer passes need the
opposite — "which locks does this call acquire, transitively?" and
"which functions can a jitted kernel reach?" — so this module builds a
program-wide index of every function/method under the scanned roots and
resolves call sites through four tiers:

  1. ``self.m()`` / ``cls.m()``: the enclosing class, its indexed bases
     and subclasses (an overridden method resolves to every override —
     the analysis is a MAY analysis, over-approximation is the sound
     direction).
  2. Receiver patterns from the committed spec (``[receivers]`` in
     ``lock_order.toml``): ``self.sessions.get(...)`` resolves through
     ``self.sessions -> SessionFabric``. Subscripts and call parens are
     stripped first, so ``self.shards[i].evict`` and
     ``self.shard_of(sid).put`` both type through their base chain.
  3. Spec ``[callbacks]``: attributes holding dynamically-bound
     callables (``self._on_evict``) that no AST walk can see.
  4. Bare names: same-module functions; method names defined by exactly
     one indexed class resolve there unless the name is on the
     common-name blacklist (``.get``/``.append``/... would otherwise
     glue every dict access into the graph).

Unresolved calls are dropped, counted in ``Index.unresolved`` — a MAY
analysis loses edges there, which is why the load-bearing dynamic edges
ride the committed callback table instead of a heuristic.
"""

from __future__ import annotations

import ast
import dataclasses
import pathlib
from typing import Optional

from scripts.lints.base import REPO, SKIP_PARTS

# names too generic to resolve by uniqueness: builtin-container verbs and
# logging/string methods that would wire dict/list/str traffic into the
# call graph as false method edges
COMMON_NAMES = frozenset({
    "get", "put", "pop", "popitem", "items", "keys", "values", "append",
    "add", "update", "copy", "clear", "extend", "remove", "insert",
    "sort", "reverse", "count", "index", "join", "split", "strip",
    "startswith", "endswith", "encode", "decode", "format", "read",
    "write", "close", "open", "flush", "seek", "send", "recv", "abort",
    "start", "stop", "run", "join", "result", "done", "submit", "group",
    "match", "search", "info", "warning", "error", "debug", "exception",
    "acquire", "release", "wait", "notify", "set", "is_set", "locked",
})


@dataclasses.dataclass
class FunctionInfo:
    qname: str  # "rel/path.py::Class.method" (nested: "outer.<locals>.f")
    name: str
    rel: str  # repo-relative file
    class_name: Optional[str]
    node: ast.AST  # FunctionDef / AsyncFunctionDef / Lambda
    # filled by analysis passes (lockorder summaries etc.)
    summary: dict = dataclasses.field(default_factory=dict)


class Index:
    """Program-wide function/method index over a set of source roots."""

    def __init__(self, spec=None):
        self.spec = spec
        self.functions: dict[str, FunctionInfo] = {}
        self.by_name: dict[str, list[str]] = {}
        self.by_class_method: dict[tuple, list[str]] = {}
        self.class_bases: dict[str, list[str]] = {}
        self.subclasses: dict[str, set] = {}
        self.modules: dict[str, dict] = {}  # rel -> {name: qname} top level
        self.imports: dict[str, dict] = {}  # rel -> {local name: (mod rel, orig)}
        self.trees: dict[str, ast.Module] = {}
        self.unresolved = 0

    # ---------------- construction ----------------

    @classmethod
    def build(cls, roots, spec=None, skip_files=()) -> "Index":
        idx = cls(spec=spec)
        for path in iter_python_files(roots):
            rel = _rel(path)
            if rel in skip_files:
                continue
            try:
                tree = ast.parse(path.read_text(), filename=str(path))
            except SyntaxError:
                continue  # the lint engine reports syntax errors
            idx._index_module(rel, tree)
        # subclass closure (single level is enough for this codebase's
        # flat hierarchies, but walk transitively anyway)
        for klass, bases in idx.class_bases.items():
            for base in bases:
                idx.subclasses.setdefault(base, set()).add(klass)
        return idx

    def _index_module(self, rel: str, tree: ast.Module) -> None:
        self.trees[rel] = tree
        self.modules.setdefault(rel, {})
        imports = self.imports.setdefault(rel, {})
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and node.module:
                mod_rel = node.module.replace(".", "/") + ".py"
                for a in node.names:
                    if a.name != "*":
                        imports[a.asname or a.name] = (mod_rel, a.name)

        def visit(node, class_name, prefix):
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    qual = (
                        f"{prefix}{child.name}" if not class_name
                        else f"{prefix}{class_name}.{child.name}"
                    )
                    qname = f"{rel}::{qual}"
                    info = FunctionInfo(
                        qname=qname, name=child.name, rel=rel,
                        class_name=class_name, node=child,
                    )
                    self.functions[qname] = info
                    self.by_name.setdefault(child.name, []).append(qname)
                    if class_name:
                        self.by_class_method.setdefault(
                            (class_name, child.name), []
                        ).append(qname)
                    else:
                        self.modules[rel].setdefault(child.name, qname)
                    visit(child, None, f"{qual}.<locals>.")
                elif isinstance(child, ast.ClassDef):
                    self.class_bases[child.name] = [
                        b.id for b in child.bases if isinstance(b, ast.Name)
                    ] + [
                        b.attr for b in child.bases
                        if isinstance(b, ast.Attribute)
                    ]
                    visit(child, child.name, prefix)
                else:
                    visit(child, class_name, prefix)

        visit(tree, None, "")

    # ---------------- class helpers ----------------

    def class_family(self, class_name: str) -> list[str]:
        """The class, its indexed ancestors, and its indexed
        descendants — the sound resolution set for a method call on an
        instance typed only by class name."""
        seen: list[str] = []
        frontier = [class_name]
        while frontier:
            k = frontier.pop()
            if k in seen:
                continue
            seen.append(k)
            frontier.extend(self.class_bases.get(k, []))
            frontier.extend(self.subclasses.get(k, ()))
        return seen

    def methods_of(self, class_name: str, method: str) -> list[str]:
        out = []
        for k in self.class_family(class_name):
            out.extend(self.by_class_method.get((k, method), []))
        return out

    # ---------------- call resolution ----------------

    def resolve_call(
        self, call: ast.Call, caller: FunctionInfo
    ) -> list[str]:
        fn = call.func
        spec = self.spec
        if isinstance(fn, ast.Name):
            # committed callback bindings first (safe(fn, ...) shims)
            if spec is not None and fn.id in spec.callbacks:
                out = []
                for target in spec.callbacks[fn.id]:
                    if "." in target:
                        klass, meth = target.rsplit(".", 1)
                        out.extend(
                            self.by_class_method.get((klass, meth), [])
                        )
                    else:
                        out.extend(self.by_name.get(target, []))
                return out
            qname = self.modules.get(caller.rel, {}).get(fn.id)
            if qname:
                return [qname]
            # nested function in the same enclosing scope
            local = [
                q for q in self.by_name.get(fn.id, ())
                if q.startswith(caller.rel + "::")
            ]
            if local:
                return local
            # cross-module: a bare name bound by `from X import f`
            imp = self.imports.get(caller.rel, {}).get(fn.id)
            if imp is not None:
                mod_rel, orig = imp
                qname = self.modules.get(mod_rel, {}).get(orig)
                if qname:
                    return [qname]
            self.unresolved += 1
            return []
        if not isinstance(fn, ast.Attribute):
            self.unresolved += 1
            return []
        attr = fn.attr
        pattern = receiver_pattern(fn.value)
        full_pattern = f"{pattern}.{attr}" if pattern else attr
        # tier 3: committed callback bindings
        if spec is not None and full_pattern in spec.callbacks:
            out = []
            for target in spec.callbacks[full_pattern]:
                if "." in target:
                    klass, meth = target.rsplit(".", 1)
                    out.extend(self.by_class_method.get((klass, meth), []))
                else:
                    out.extend(self.by_name.get(target, []))
            return out
        # tier 1: self/cls
        if pattern in ("self", "cls") and caller.class_name:
            hits = self.methods_of(caller.class_name, attr)
            if hits:
                return hits
        # tier 2: spec receiver typing
        if spec is not None:
            klass = spec.receivers.get(pattern)
            if klass is not None:
                hits = self.methods_of(klass, attr)
                if hits:
                    return hits
        # tier 4: unique method name, blacklist-guarded
        if attr not in COMMON_NAMES:
            owners = {
                k for (k, m) in self.by_class_method if m == attr
            }
            if len(owners) == 1:
                return self.by_class_method[(next(iter(owners)), attr)]
            mods = [
                q for mod in self.modules.values()
                for n, q in mod.items() if n == attr
            ]
            if not owners and len(mods) == 1:
                return mods
        self.unresolved += 1
        return []


def receiver_pattern(expr: ast.AST) -> str:
    """Normalize a receiver expression to a dotted pattern: subscripts
    and call parentheses stripped (``self.shards[i]`` -> "self.shards",
    ``self.shard_of(sid)`` -> "self.shard_of")."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        base = receiver_pattern(expr.value)
        return f"{base}.{expr.attr}" if base else expr.attr
    if isinstance(expr, ast.Subscript):
        return receiver_pattern(expr.value)
    if isinstance(expr, ast.Call):
        return receiver_pattern(expr.func)
    return ""


def _rel(path: pathlib.Path) -> str:
    resolved = path.resolve()
    try:
        return str(resolved.relative_to(REPO))
    except ValueError:
        return str(path)


def iter_python_files(roots) -> list[pathlib.Path]:
    out = []
    for root in roots:
        p = (
            pathlib.Path(root)
            if pathlib.Path(root).is_absolute() else REPO / root
        )
        if p.is_file():
            out.append(p)
            continue
        for f in sorted(p.rglob("*.py")):
            if not SKIP_PARTS.intersection(f.parts):
                out.append(f)
    return out
