"""shard_map contract pass against the committed SPMD spec
(``scripts/analysis/spmd_spec.toml``, ISSUE 19 tentpole).

The sharded kernels (parallel/) promise PR 17-18's D-invariance
contract: one 1xD provider mesh, every collective on the declared axis,
candidate structure bit-identical at any device count. The end-to-end
replay gates prove the promise holds for the committed goldens; this
pass localizes WHY it holds, per call site, and catches the drift the
replay only reports as "diverged at tick 7":

  S1 contract shape: every ``shard_map`` call/decorator carries
     ``mesh=``, ``in_specs=`` and ``out_specs=`` (a missing spec is
     implicit replication that happens to work at D=1 and silently
     gathers at D>1).

  S2 axis names: every ``P(...)`` axis and every collective axis
     operand (``psum``/``pmax``/``pmin``/``all_gather``/``axis_index``/
     ...) must RESOLVE to an axis declared in ``[mesh] axes`` — through
     a string literal, a module constant, an enclosing parameter
     default, or a committed ``[axis_aliases]`` name. An operand the
     pass cannot resolve is itself a finding: the spec stays total,
     exactly like the lock pass's unclassifiable-lock rule.

  S3 spec arity: ``in_specs`` tuple length must match the wrapped
     function's parameter count, and ``out_specs`` tuple length its
     returned tuple length, whenever both sides are statically
     determinable (MAY analysis — a pytree-valued spec variable counts
     as one argument slot, matching shard_map's prefix semantics).

  S4 collective placement: a collective reached from code that is NOT
     under any shard_map body (lexically or through the call graph) has
     no axis to talk over — it works in tests that never build a mesh
     and fails on the flag-flip day.

  S5 D-invariance: reading the device count inside a traced region
     (``jax.device_count``/``local_device_count``/``jax.devices``), or
     any ``[d_invariance] sources`` flow into a guarded call
     (``pick_tile``) — the tile policy must be a function of T only,
     the invariant jax_arena._gen_plan encodes by computing the tile
     BEFORE asking for D.

Escape: ``# lint: spmd-ok`` on the line (staleness-audited). The
runtime twin for the recompile half of the staging story is
``protocol_tpu/utils/jitwitness.py``.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Optional

from scripts.analysis import purity
from scripts.analysis.callgraph import Index, receiver_pattern
from scripts.analysis.spec import _load_toml
from scripts.lints.base import Finding, REPO

RULE = "spmd-contract"
SUPPRESS = "spmd-ok"

DEFAULT_ROOTS = purity.DEFAULT_ROOTS

SPEC_PATH = os.path.join(os.path.dirname(__file__), "spmd_spec.toml")

# which operand carries the axis name, per collective
_AXIS_ARG_POS = {"axis_index": 0}
_DEFAULT_AXIS_POS = 1


@dataclasses.dataclass(frozen=True)
class SpmdSpec:
    axes: tuple
    rank: int
    axis_aliases: tuple
    collectives: tuple
    d_sources: tuple
    d_guarded: tuple
    quantizers: tuple


def load_spmd_spec(path: Optional[str] = None) -> SpmdSpec:
    doc = _load_toml(path or SPEC_PATH)
    mesh = doc.get("mesh", {})
    return SpmdSpec(
        axes=tuple(mesh.get("axes", [])),
        rank=int(mesh.get("rank", 1)),
        axis_aliases=tuple(
            doc.get("axis_aliases", {}).get("names", [])
        ),
        collectives=tuple(doc.get("collectives", {}).get("ops", [])),
        d_sources=tuple(doc.get("d_invariance", {}).get("sources", [])),
        d_guarded=tuple(doc.get("d_invariance", {}).get("guarded", [])),
        quantizers=tuple(doc.get("quantizers", {}).get("names", [])),
    )


def _callable_name(fn: ast.AST) -> str:
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return ""


class SpmdChecker:
    def __init__(
        self, roots=DEFAULT_ROOTS, index: Optional[Index] = None,
        spec: Optional[SpmdSpec] = None,
    ):
        self.index = index if index is not None else Index.build(roots)
        self.spec = spec if spec is not None else load_spmd_spec()
        self.purity = purity.PurityChecker(roots, index=self.index)
        self.findings: list[Finding] = []
        self.consumed: set = set()
        self._lines: dict[str, list] = {}
        self._module_strs: dict[str, dict] = {}

    # ---------------- driver ----------------

    def run(self) -> list[Finding]:
        sharded = self._sharded_functions()
        region = self._sharded_region(sharded)
        entries = self.purity.jit_entries()
        jit_reach = self.purity.closure(entries)
        for qname, info in sorted(self.index.functions.items()):
            self._check_function(info, region, jit_reach)
        self._check_module_level()
        return self.findings

    # ---------------- shard_map site discovery ----------------

    def _shard_map_call(self, node: ast.AST) -> Optional[ast.Call]:
        """The Call carrying shard_map's keywords: the call itself, or
        the ``partial(shard_map, ...)`` decorator shape."""
        if not isinstance(node, ast.Call):
            return None
        name = _callable_name(node.func)
        if name == "shard_map":
            return node
        if name == "partial" and node.args and _callable_name(
            node.args[0]
        ) == "shard_map":
            return node
        return None

    def _sharded_functions(self) -> dict:
        """qname -> shard_map Call for every function whose body runs
        under shard_map: decorator form plus the call form's wrapped
        target resolved in-file."""
        out = {}
        for qname, info in self.index.functions.items():
            for dec in getattr(info.node, "decorator_list", ()):
                call = self._shard_map_call(dec)
                if call is not None:
                    call._spmd_parent_def = info
                    out[qname] = call
        for rel, tree in self.index.trees.items():
            for node in ast.walk(tree):
                call = self._shard_map_call(node)
                if call is None or call is not node:
                    continue
                target = None
                if node.args and isinstance(
                    node.args[0] if _callable_name(node.func)
                    == "shard_map" else None, ast.Name
                ):
                    target = node.args[0].id
                elif _callable_name(node.func) == "partial":
                    if len(node.args) > 1 and isinstance(
                        node.args[1], ast.Name
                    ):
                        target = node.args[1].id
                if target is None:
                    continue
                local = [
                    q for q in self.index.by_name.get(target, ())
                    if self.index.functions[q].rel == rel
                ]
                for q in local:
                    out.setdefault(q, node)
        return out

    def _sharded_region(self, sharded: dict) -> set:
        """Call-graph closure of the shard_map bodies (nested defs ride
        lexically; helpers ride resolve_call edges)."""
        return self.purity.closure(set(sharded))

    def _in_region(self, qname: str, region: set) -> bool:
        if qname in region:
            return True
        rel, qual = qname.split("::", 1)
        parts = qual.split(".<locals>.")
        for depth in range(1, len(parts)):
            if f"{rel}::" + ".<locals>.".join(parts[:depth]) in region:
                return True
        return False

    # ---------------- per-function checks ----------------

    def _check_function(self, info, region: set, jit_reach: set) -> None:
        tainted: set[str] = set()
        for node in _ordered_own(info.node):
            call = self._shard_map_call(node)
            if call is not None:
                self._check_shard_map(info, call, node)
            if isinstance(node, ast.Assign):
                if any(
                    self._d_tainted(v, tainted)
                    for v in ast.walk(node.value)
                ):
                    tainted.update(
                        t.id for tgt in node.targets
                        for t in ast.walk(tgt)
                        if isinstance(t, ast.Name)
                    )
            if isinstance(node, ast.Call):
                self._check_collective(info, node, region)
                self._check_guarded(info, node, tainted)
                self._check_device_read(info, node, jit_reach)
        # a nested def's decorators execute in THIS scope and are
        # yielded by _ordered_own; its body is visited under its own
        # qname so region membership stays per-innermost-function

    def _check_module_level(self) -> None:
        """Module-level shard_map/collective sites (outside any def)."""
        for rel, tree in self.index.trees.items():
            fn_nodes = {
                id(i.node) for i in self.index.functions.values()
                if i.rel == rel
            }

            def walk(node):
                for child in ast.iter_child_nodes(node):
                    if id(child) in fn_nodes:
                        continue
                    call = self._shard_map_call(child)
                    if call is not None:
                        self._check_shard_map_rel(rel, call, None)
                    walk(child)

            walk(tree)

    # ---------------- S1-S3: the shard_map contract ----------------

    def _check_shard_map(self, info, call, site) -> None:
        self._check_shard_map_rel(info.rel, call, info)

    def _check_shard_map_rel(self, rel, call, info) -> None:
        kws = {kw.arg: kw.value for kw in call.keywords}
        for required in ("mesh", "in_specs", "out_specs"):
            if required not in kws:
                self._find(
                    rel, call,
                    f"shard_map without {required}= — implicit "
                    "replication works at D=1 and silently diverges "
                    "on a real mesh; state the contract",
                )
        for spec_kw in ("in_specs", "out_specs"):
            if spec_kw in kws:
                self._check_partition_axes(rel, kws[spec_kw], info)
        wrapped = self._wrapped_fn(rel, call)
        if wrapped is None:
            return
        in_specs = kws.get("in_specs")
        if isinstance(in_specs, ast.Tuple):
            nparams = len(_params(wrapped.node))
            if len(in_specs.elts) != nparams:
                self._find(
                    rel, in_specs,
                    f"in_specs has {len(in_specs.elts)} entries but "
                    f"'{wrapped.name}' takes {nparams} arguments — "
                    "the mismatch shifts every spec one slot",
                )
        out_specs = kws.get("out_specs")
        if isinstance(out_specs, ast.Tuple):
            sizes = _return_tuple_sizes(wrapped.node)
            if sizes and all(s != len(out_specs.elts) for s in sizes):
                self._find(
                    rel, out_specs,
                    f"out_specs has {len(out_specs.elts)} entries but "
                    f"'{wrapped.name}' returns "
                    f"{'/'.join(str(s) for s in sorted(sizes))} values",
                )

    def _wrapped_fn(self, rel, call):
        """FunctionInfo the shard_map wraps, when resolvable."""
        target = None
        if _callable_name(call.func) == "shard_map":
            if call.args and isinstance(call.args[0], ast.Name):
                target = call.args[0].id
        elif len(call.args) > 1 and isinstance(call.args[1], ast.Name):
            target = call.args[1].id
        if target is None:
            # decorator form: partial(shard_map, ...) with no target
            # rides on a def — find it by the decorator backlink
            parent = getattr(call, "_spmd_parent_def", None)
            return parent
        local = [
            q for q in self.index.by_name.get(target, ())
            if self.index.functions[q].rel == rel
        ]
        if len(local) == 1:
            return self.index.functions[local[0]]
        return None

    def _check_partition_axes(self, rel, spec_expr, info) -> None:
        for sub in ast.walk(spec_expr):
            if not (
                isinstance(sub, ast.Call)
                and _callable_name(sub.func) in ("P", "PartitionSpec")
            ):
                continue
            for a in sub.args:
                axis = self._resolve_axis(rel, a, info)
                if axis is _UNRESOLVED:
                    self._find(
                        rel, a,
                        f"cannot resolve P(...) axis operand "
                        f"{ast.unparse(a)!r} — use a literal, a module "
                        "constant, or a spec'd [axis_aliases] name",
                    )
                elif axis is not None and axis not in self.spec.axes:
                    self._find(
                        rel, a,
                        f"P(...) names axis {axis!r} which is not in "
                        f"the declared mesh axes {list(self.spec.axes)}",
                    )

    # ---------------- S2/S4: collectives ----------------

    def _check_collective(self, info, call, region) -> None:
        fname = _callable_name(call.func)
        if fname not in self.spec.collectives:
            return
        if not isinstance(call.func, ast.Attribute):
            return
        root = receiver_pattern(call.func.value).split(".", 1)[0]
        if root not in ("lax", "jax"):
            return
        if not self._in_region(info.qname, region):
            self._find(
                info.rel, call,
                f"collective lax.{fname} outside any shard_map region "
                "— there is no mesh axis to communicate over here",
            )
        pos = _AXIS_ARG_POS.get(fname, _DEFAULT_AXIS_POS)
        axis_expr = None
        for kw in call.keywords:
            if kw.arg in ("axis_name", "axis"):
                axis_expr = kw.value
        if axis_expr is None and len(call.args) > pos:
            axis_expr = call.args[pos]
        if axis_expr is None:
            self._find(
                info.rel, call,
                f"collective lax.{fname} without an axis name — it "
                "must name the spec'd mesh axis",
            )
            return
        axis = self._resolve_axis(info.rel, axis_expr, info)
        if axis is _UNRESOLVED:
            self._find(
                info.rel, call,
                f"cannot resolve the axis operand of lax.{fname} "
                f"({ast.unparse(axis_expr)!r}) — use a literal, a "
                "module constant, or a spec'd [axis_aliases] name",
            )
        elif axis is not None and axis not in self.spec.axes:
            self._find(
                info.rel, call,
                f"lax.{fname} names axis {axis!r} which is not in the "
                f"declared mesh axes {list(self.spec.axes)}",
            )

    # ---------------- S5: D-invariance ----------------

    def _d_tainted(self, node: ast.AST, tainted: set) -> bool:
        if isinstance(node, ast.Name) and node.id in tainted:
            return True
        if isinstance(node, ast.Call):
            pat = receiver_pattern(node.func)
            if pat in self.spec.d_sources:
                return True
        if isinstance(node, ast.Attribute):
            if receiver_pattern(node) in self.spec.d_sources:
                return True
        return False

    def _check_guarded(self, info, call, tainted) -> None:
        if _callable_name(call.func) not in self.spec.d_guarded:
            return
        exprs = list(call.args) + [kw.value for kw in call.keywords]
        for e in exprs:
            if any(
                self._d_tainted(sub, tainted) for sub in ast.walk(e)
            ):
                self._find(
                    info.rel, call,
                    f"'{_callable_name(call.func)}' argument derives "
                    "from the device count — the tile policy must be "
                    "a function of T only (candidate structure must "
                    "be bit-identical at any D)",
                )
                return

    def _check_device_read(self, info, call, jit_reach) -> None:
        pat = receiver_pattern(call.func)
        if pat not in (
            "jax.device_count", "jax.local_device_count", "jax.devices"
        ):
            return
        if info.qname in jit_reach or self._in_region(
            info.qname, jit_reach
        ):
            self._find(
                info.rel, call,
                f"{pat}() inside a traced region — bakes the device "
                "count into the executable, breaking the D-invariance "
                "contract",
            )

    # ---------------- axis resolution ----------------

    def _resolve_axis(self, rel, expr, info):
        """The axis STRING an operand resolves to; None when the
        operand is legitimately axis-free (None / empty P()); the
        _UNRESOLVED sentinel otherwise."""
        if isinstance(expr, ast.Constant):
            if expr.value is None:
                return None
            if isinstance(expr.value, str):
                return expr.value
            return _UNRESOLVED
        if isinstance(expr, ast.Tuple):
            # P(("p",)) multi-axis slot: resolve each element
            for e in expr.elts:
                r = self._resolve_axis(rel, e, info)
                if r is _UNRESOLVED or (
                    r is not None and r not in self.spec.axes
                ):
                    return r
            return None
        if isinstance(expr, ast.Name):
            if expr.id in self.spec.axis_aliases:
                # the conventional carrier of the (single) mesh axis
                return self.spec.axes[0] if self.spec.axes else None
            const = self._module_str_consts(rel).get(expr.id)
            if const is not None:
                return const
            if info is not None:
                d = _param_default_str(info.node, expr.id)
                if d is not None:
                    return d
            return _UNRESOLVED
        if isinstance(expr, ast.Attribute):
            # PROVIDER_AXIS-style constant on an imported module
            const = self._module_str_consts(rel).get(expr.attr)
            if const is not None:
                return const
            if expr.attr in self.spec.axis_aliases:
                return self.spec.axes[0] if self.spec.axes else None
            return _UNRESOLVED
        return _UNRESOLVED

    def _module_str_consts(self, rel) -> dict:
        got = self._module_strs.get(rel)
        if got is not None:
            return got
        out: dict = {}
        tree = self.index.trees.get(rel)
        if tree is not None:
            for st in tree.body:
                if isinstance(st, ast.Assign) and isinstance(
                    st.value, ast.Constant
                ) and isinstance(st.value.value, str):
                    for tgt in st.targets:
                        if isinstance(tgt, ast.Name):
                            out[tgt.id] = st.value.value
        # imported constants: from X import PROVIDER_AXIS
        for name, (mod_rel, orig) in self.index.imports.get(
            rel, {}
        ).items():
            tree = self.index.trees.get(mod_rel)
            if tree is None:
                continue
            for st in tree.body:
                if isinstance(st, ast.Assign) and isinstance(
                    st.value, ast.Constant
                ) and isinstance(st.value.value, str) and any(
                    isinstance(t, ast.Name) and t.id == orig
                    for t in st.targets
                ):
                    out[name] = st.value.value
        self._module_strs[rel] = out
        return out

    # ---------------- reporting ----------------

    def _find(self, rel: str, node, msg: str) -> None:
        line = getattr(node, "lineno", 0)
        lines = self._file_lines(rel)
        if lines and 1 <= line <= len(lines):
            if f"lint: {SUPPRESS}" in lines[line - 1]:
                self.consumed.add((rel, line))
                return
        f = Finding(RULE, rel, line, msg)
        if f not in self.findings:
            self.findings.append(f)

    def _file_lines(self, rel: str):
        if rel not in self._lines:
            try:
                self._lines[rel] = (REPO / rel).read_text().splitlines()
            except OSError:
                self._lines[rel] = []
        return self._lines[rel]


class _Unresolved:
    pass


_UNRESOLVED = _Unresolved()


def _params(fn: ast.AST) -> list:
    a = fn.args
    return list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)


def _param_default_str(fn: ast.AST, name: str) -> Optional[str]:
    """The string default of parameter ``name`` anywhere in the lexical
    chain of ``fn`` (the sharded builders thread ``axis: str = "p"``)."""
    for node in ast.walk(fn):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        a = node.args
        pos = list(a.posonlyargs) + list(a.args)
        for p, d in zip(pos[len(pos) - len(a.defaults):], a.defaults):
            if p.arg == name and isinstance(d, ast.Constant) and (
                isinstance(d.value, str)
            ):
                return d.value
        for p, d in zip(a.kwonlyargs, a.kw_defaults):
            if p.arg == name and isinstance(d, ast.Constant) and (
                isinstance(d.value, str)
            ):
                return d.value
    return None


def _return_tuple_sizes(fn: ast.AST) -> set:
    """Sizes of tuple-literal returns of ``fn`` itself (nested defs
    excluded); empty when any return defeats static counting."""
    sizes: set = set()
    for node in ast.walk(fn):
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef)
        ) and node is not fn:
            continue
        if isinstance(node, ast.Return):
            if isinstance(node.value, ast.Tuple):
                sizes.add(len(node.value.elts))
            else:
                return set()
    return sizes


def _ordered_own(root: ast.AST):
    """Pre-order, source-order traversal of ``root``'s OWN statements:
    a nested def is yielded (with its decorator expressions, which run
    in this scope) but not descended into — its body is checked under
    its own qname. ast.walk would be wrong twice over: breadth-first
    order breaks assignment-before-use taint, and descending into
    nested defs misattributes their call sites to the outer scope."""
    for child in ast.iter_child_nodes(root):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield child
            for dec in child.decorator_list:
                yield dec
                yield from _ordered_own(dec)
            continue
        yield child
        yield from _ordered_own(child)


def run(roots=DEFAULT_ROOTS, index=None, spec=None) -> list[Finding]:
    return SpmdChecker(roots, index=index, spec=spec).run()
