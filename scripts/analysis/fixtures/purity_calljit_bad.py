"""Seeded call-form jit entries (the seeded marker lines are the
oracle): kernels that are never decorated — they are wrapped by a
``jax.jit(fn)`` / ``jax.jit(shard_map(fn, ...))`` CALL at module level
or inside a builder — yet must still be treated as trace roots. Each
wrapped body carries one purity violation the decorator-only scan used
to miss entirely."""

import time

import jax
import numpy as np
from functools import partial

from jax.experimental.shard_map import shard_map


def _sync_body(cost):
    return float(cost.item())  # SEED: jax-purity


jit_sync = jax.jit(_sync_body)


def _clock_body(cost):
    return cost * time.time()  # SEED: jax-purity


jit_clock = jax.jit(_clock_body)


def _branch_body(cost, eps):
    if eps > 0:  # SEED: jax-purity
        cost = cost / eps
    return cost


# static_argnames names "k" only: eps stays traced, the branch fires
jit_branch = jax.jit(_branch_body, static_argnames=("k",))


def _sharded_body(cost):
    return np.asarray(cost)  # SEED: jax-purity


jit_sharded = jax.jit(
    shard_map(_sharded_body, mesh=None, in_specs=(), out_specs=()),
)


def _partial_body(cost, scale):
    return cost + np.zeros(4)  # SEED: jax-purity


jit_partial = jax.jit(partial(_partial_body, scale=2.0))


def build_kernel(mesh):
    """Builder-local call form: the jitted closure is a nested def."""

    def _local_body(cost):
        return cost.tolist()  # SEED: jax-purity

    return jax.jit(_local_body)
