"""Seeded wire-v2 protocol violations (the seeded marker lines are
the oracle). Handler shapes mirror the servicer; ``pb``/``unblob`` are
AST-level stand-ins — the checker never imports fixtures."""


class BadServicer:
    def deadline_after_apply(self, request, context, session):
        # the PR 9 review-caught mutation: deadline honored after the
        # delta applied — an abort here double-applies on retry
        with session.lock:
            try:
                rows = unblob(request.provider_rows, None)
            except ValueError:
                context.abort(None, "bad frame")
            session.apply_delta(rows, {}, rows, {})
            self._check_deadline(context, "delta")  # SEED: protocol-sm
            session.tick += 1
            session.last_delta_crc = 7
            return pb.AssignDeltaResponse(session_ok=True)

    def unmarked_refusal(self, request, session):
        if session.evicted:
            return pb.AssignDeltaResponse(  # SEED: protocol-sm
                session_ok=False, error="nope, try later",
            )
        session.tick += 1
        session.last_delta_crc = 1
        return pb.AssignDeltaResponse(session_ok=True)

    def computed_refusal(self, request, session):
        msg = "over quota"
        session.tick += 1
        session.last_delta_crc = 5
        if session.evicted:
            return pb.AssignDeltaResponse(session_ok=False, error=msg)  # SEED: protocol-sm
        return pb.AssignDeltaResponse(session_ok=True)

    def ack_before_crc(self, request, session):
        if request.tick == 0:
            return pb.AssignDeltaResponse(session_ok=True)  # SEED: protocol-sm
        session.last_delta_crc = 9
        return pb.AssignDeltaResponse(session_ok=True)

    def flush_after_ack(self, request, session):
        session.tick += 1
        session.last_delta_crc = 3
        try:
            return pb.AssignDeltaResponse(session_ok=True)
        finally:
            self.ckpt.flush_locked(session)  # SEED: protocol-sm

    def decode_after_mutation(self, request, session):
        session.apply_delta(None, {}, None, {})
        try:
            rows = unblob(request.provider_rows, None)  # SEED: protocol-sm
        except ValueError:
            rows = None
        del rows
        session.tick += 1
        session.last_delta_crc = 2
        return pb.AssignDeltaResponse(session_ok=True)

    def unhardened_decode(self, request, session):
        rows = unblob(request.provider_rows, None)  # SEED: protocol-sm
        session.apply_delta(rows, {}, rows, {})
        session.tick += 1
        session.last_delta_crc = 4
        return pb.AssignDeltaResponse(session_ok=True)
