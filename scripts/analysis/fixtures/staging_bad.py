"""Seeded jit staging hazards (the seeded marker lines are the
oracle): static-argname misses, mutable host-state captures, and
polymorphic compile keys — the recompile-per-tick mutation class the
runtime jit-cache witness counts live."""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

_CACHE = {}
_SEEN = []


@partial(jax.jit, static_argnames=("tile",))
def bad_static_miss(
    cost,
    tile: int,
    k: int,  # SEED: jax-retrace
):
    return cost[:k] * tile


@jax.jit
def bad_dict_capture(cost):
    key = 4
    if key not in _CACHE:  # SEED: jax-retrace
        return cost
    return cost * 2


@jax.jit
def bad_list_capture(cost):
    _SEEN.append(1)  # SEED: jax-retrace
    return cost


def build_logged(mesh):
    log = []

    def inner(cost):
        log.append(2)  # SEED: jax-retrace
        return cost * 2

    return jax.jit(inner)


@partial(jax.jit, static_argnames=("n",))
def take_n(cost, n: int):
    return cost[:n]


def caller_churny_static(cost, mask):
    n_open = int(jnp.sum(mask))
    return take_n(cost, n=n_open)  # SEED: jax-retrace


def build_pad(pad):
    def run(cost):
        return jnp.pad(cost, (0, pad))

    return jax.jit(run)


def caller_churny_builder(cost, mask):
    rows = np.flatnonzero(mask)
    run = build_pad(rows.size)  # SEED: jax-retrace
    return run(cost)


def caller_dtype_fork(cost, wide):
    run = build_pad(  # SEED: jax-retrace
        jnp.zeros(4, dtype=jnp.float64 if wide else jnp.float32).size,
    )
    return run(cost)
