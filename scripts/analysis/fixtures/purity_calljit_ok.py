"""Clean twin of purity_calljit_bad: the same call-form jit shapes with
trace-pure bodies — static_argnames honored (branching on a static is
the legal specialization idiom), shape probes whitelisted, dtypes
explicit. Must come back silent."""

import jax
import jax.numpy as jnp
import numpy as np
from functools import partial

from jax.experimental.shard_map import shard_map


def _pure_body(cost):
    return (cost * jnp.float32(2.0)).sum()


jit_pure = jax.jit(_pure_body)


def _specialized_body(cost, k):
    if k > 2:  # static: named in static_argnames below
        return cost[:k]
    return cost


jit_specialized = jax.jit(_specialized_body, static_argnames=("k",))


def _shape_probe_body(cost):
    if cost.ndim == 1:  # shape probing is trace-time constant
        cost = cost[None, :]
    return cost + np.zeros(cost.shape, dtype=np.float32)


jit_sharded = jax.jit(
    shard_map(_shape_probe_body, mesh=None, in_specs=(), out_specs=()),
)


def _partial_body(cost, scale):
    return cost * scale


jit_partial = jax.jit(partial(_partial_body, scale=2.0))


def build_kernel(mesh):
    def _local_body(cost):
        return cost + jnp.ones(cost.shape, dtype=jnp.float32)

    return jax.jit(_local_body)
