"""Seeded shard_map contract violations (the seeded marker lines are
the oracle): a missing spec kwarg, undeclared/unresolvable axis names,
spec-arity mismatches, collectives with a bad or missing axis or
outside any sharded region, and D-invariance breaks — the mutation
class that works at D=1 and silently diverges on a real mesh."""

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

MESH = None
_mystery = object()


@jax.jit
@partial(shard_map, mesh=MESH, in_specs=(P("p", None),))  # SEED: spmd-contract
def bad_missing_out(cost):
    return lax.psum(cost, "p")


@jax.jit
@partial(
    shard_map,
    mesh=MESH,
    in_specs=(P("q", None),),  # SEED: spmd-contract
    out_specs=P(),
    check_vma=False,
)
def bad_axis_in_spec(cost):
    return jnp.sum(cost)


@jax.jit
@partial(
    shard_map,
    mesh=MESH,
    in_specs=(P(_mystery, None),),  # SEED: spmd-contract
    out_specs=P(),
)
def bad_unresolvable_spec(cost):
    return cost


@jax.jit
@partial(
    shard_map,
    mesh=MESH,
    in_specs=(P("p", None), P()),  # SEED: spmd-contract
    out_specs=P(),
)
def bad_in_arity(cost):
    return cost


@jax.jit
@partial(
    shard_map,
    mesh=MESH,
    in_specs=(P("p", None),),
    out_specs=(P(), P()),  # SEED: spmd-contract
)
def bad_out_arity(cost):
    return cost, cost, cost


@jax.jit
@partial(shard_map, mesh=MESH, in_specs=(P("p", None),), out_specs=P())
def bad_collective_axis(cost):
    return lax.psum(cost, "q")  # SEED: spmd-contract


@jax.jit
@partial(shard_map, mesh=MESH, in_specs=(P("p", None),), out_specs=P())
def bad_collective_no_axis(cost):
    return lax.psum(cost)  # SEED: spmd-contract


@jax.jit
@partial(
    shard_map, mesh=MESH, in_specs=(P("p", None), P()), out_specs=P(),
)
def bad_collective_opaque_axis(cost, which):
    return lax.pmax(cost, which)  # SEED: spmd-contract


def host_combine(cost):
    return lax.psum(cost, "p")  # SEED: spmd-contract


@jax.jit
def bad_device_read(cost):
    return cost / jax.device_count()  # SEED: spmd-contract


def pick_tile(T, cap=1024):
    return min(T, cap)


def bad_tile_policy(T):
    D = jax.local_device_count()
    return pick_tile(T, cap=T // D)  # SEED: spmd-contract
