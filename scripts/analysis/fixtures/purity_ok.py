"""Clean twin of purity_bad.py: the legal idioms — shape/dtype
specialization, is-None defaults, static_argnames branching, dtype-
pinned constructors, lax control flow — must produce zero findings."""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


@jax.jit
def ok_shape_branch(cost, task_order=None):
    P, T = cost.shape
    if task_order is None:
        task_order = jnp.arange(T, dtype=jnp.int32)
    if cost.ndim != 2:
        raise ValueError("cost must be [P, T]")

    def step(avail, col):
        masked = jnp.where(avail, col, 1e9)
        p = jnp.argmin(masked).astype(jnp.int32)
        return avail.at[p].set(False), p

    _, picks = lax.scan(step, jnp.ones(P, dtype=bool), cost.T)
    return picks[task_order]


@partial(jax.jit, static_argnames=("tile",))
def ok_static_branch(cost, tile=128):
    if tile <= 0:
        raise ValueError("tile must be positive")
    pinned = np.zeros(4, np.float32)  # dtype pinned: no promotion
    return cost + jnp.asarray(pinned)


def ok_helper(x):
    return jnp.maximum(x, 0.0)


@jax.jit
def ok_via_helper(cost):
    return ok_helper(cost)
