"""Seeded lock-order violations (the seeded marker lines are the
oracle): the REORDERED-ACQUISITION mutation class — holding the fabric
budget leaf while entering a shard, directly and through a call chain
the per-file lint cannot see."""

import threading


class SessionStore:
    def __init__(self):
        self._lock = threading.Lock()

    def evict(self, sid):
        with self._lock:
            self._let_go_locked(sid)

    def _let_go_locked(self, sid):
        pass


class SessionFabric:
    def __init__(self):
        self._budget_lock = threading.Lock()
        self.shards = [SessionStore()]

    def pressure_backwards(self, shard):
        # interprocedural: evict() takes the shard lock three frames in
        with self._budget_lock:
            shard.evict("sid")  # SEED: lock-order

    def nested_backwards(self, shard):
        with self._budget_lock:
            with shard._lock:  # SEED: lock-order
                pass
