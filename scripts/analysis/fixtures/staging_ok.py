"""Clean twin of staging_bad.py: the SAME shapes, hazard-free — the
near-misses the retrace pass must NOT flag. Static argnames cover every
Python-typed parameter, union-annotated scalars ride traced, captures
are immutable, and every data-dependent compile key is laundered
through a committed quantizer or the ``*= 2`` doubling ladder."""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

SCALE = 1.5
_DIMS = (4, 8)


def _pow2_pad(n, lo=8):
    p = lo
    while p < n:
        p *= 2
    return p


@partial(jax.jit, static_argnames=("tile", "k"))
def ok_static_covered(cost, tile: int, k: int):
    return cost[:k] * tile


@jax.jit
def ok_union_scalar(cost, eps: float | jax.Array, state: tuple | None):
    if state is None:
        return cost + eps
    return cost + eps + state[0]


@jax.jit
def ok_immutable_capture(cost):
    out = []
    out.append(cost * SCALE)
    return out[0] + _DIMS[0]


def ok_shape_static(cost):
    # shape-derived statics add no recompile: shapes already key the cache
    return ok_static_covered(cost, tile=cost.shape[0], k=4)


def build_pad(pad):
    def run(cost):
        return jnp.pad(cost, (0, pad))

    return jax.jit(run)


def ok_quantized_builder(cost, mask):
    rows = np.flatnonzero(mask)
    pad = _pow2_pad(rows.size)
    run = build_pad(pad)
    return run(cost)


@partial(jax.jit, static_argnames=("budget",))
def take_budget(cost, budget: int):
    return cost[:budget]


def ok_doubling_ladder(cost, mask):
    n_open = int(jnp.sum(mask))
    budget = 64
    while budget < n_open:
        budget *= 2
    return take_budget(cost, budget=budget)
