"""Clean twin of purity_repair_bad: the same repair-builder call
shapes — lru_cache'd builders returning ``jax.jit(fn)`` over scan
folds and a ``jax.jit(shard_map(fn, ...))`` twin — with trace-pure
bodies (device-side reductions, jnp sentinels, no host syncs). Must
come back silent."""

from functools import lru_cache

import jax
import jax.numpy as jnp
from jax import lax

from jax.experimental.shard_map import shard_map


@lru_cache(maxsize=32)
def build_repair_forward(Pn, kk):
    def forward_rows(cost, t_ids):
        neg, idx = lax.top_k(-cost.T, kk)
        worst = (-neg[:, -1]).max()  # stays on device
        return idx, worst

    return jax.jit(forward_rows)


@lru_cache(maxsize=32)
def build_repair_enter(tile, n_tiles):
    def enter_scan(cost, thresh):
        def step(_, t0):
            block = lax.dynamic_slice_in_dim(cost, t0, tile, axis=1)
            hit = block <= thresh[None, :]
            return None, jnp.any(hit, axis=0)

        _, enter = lax.scan(
            step, None, jnp.arange(n_tiles, dtype=jnp.int32) * tile
        )
        return enter

    return jax.jit(enter_scan)


@lru_cache(maxsize=32)
def build_repair_reverse_sharded(mesh, r):
    def reverse_pools(pool_c, pool_t):
        neg, m = lax.top_k(-pool_c, r)
        return jnp.take_along_axis(pool_t, m, axis=1), -neg

    return jax.jit(
        shard_map(reverse_pools, mesh=mesh, in_specs=(), out_specs=())
    )
