"""Clean twin of spmd_bad.py: the SAME shapes, contract-honoring — the
idioms the shard_map pass must NOT flag. Full spec kwargs, axes that
resolve through literals / the ``axis`` alias / the PROVIDER_AXIS
module constant, matching spec arity, collectives only under sharded
bodies, and a tile policy that is a function of T alone."""

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

PROVIDER_AXIS = "p"


def build_phase(mesh, axis="p"):
    @jax.jit
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(axis, None), P(None)),
        out_specs=(P(), P()),
        check_vma=False,
    )
    def run(cost_local, price):
        shard = lax.axis_index(axis)
        total = lax.psum(cost_local, axis)
        best = lax.pmax(price, PROVIDER_AXIS)
        return total, best + shard

    return run


def _gather_body(x):
    return lax.all_gather(x, "p")


gathered = jax.jit(
    shard_map(
        _gather_body, mesh=None, in_specs=(P("p"),), out_specs=P("p"),
    )
)


def pick_tile(T, cap=1024):
    return min(T, cap)


def plan_tiles(T):
    # tile policy is a function of T only; the device count is read
    # host-side AFTER the tile is fixed (never flows into pick_tile)
    tile = pick_tile(T, cap=max(1, T // 8))
    D = jax.device_count()
    return tile, D


@jax.jit
def traced_entry(cost):
    return jnp.sum(cost)
