"""Seeded DROPPED-LOCK mutation (the seeded marker line is the oracle): a
``*_locked`` helper — the repo's called-under-lock naming contract —
invoked with nothing held."""

import threading


class SessionStore:
    def __init__(self):
        self._lock = threading.Lock()

    def sweep(self):
        self._expire_locked()  # SEED: lock-order

    def sweep_correct(self):
        with self._lock:
            self._expire_locked()

    def _expire_locked(self):
        pass
