"""Seeded repair-kernel entries (the seeded marker lines are the
oracle): the warm-path candidate-repair builder shapes from
parallel/sparse.py — an lru_cache'd builder whose kernel is returned
through a ``jax.jit(fn)`` CALL, a scan-body fold, and a
``jax.jit(shard_map(fn, ...))`` sharded twin — each hiding one host
sync inside the traced body. A repair kernel that syncs per chunk
would serialize the whole O(churn) batch loop on device round-trips,
so the lint must see through both call forms."""

from functools import lru_cache

import jax
import numpy as np
from jax import lax

from jax.experimental.shard_map import shard_map


@lru_cache(maxsize=32)
def build_repair_forward(Pn, kk):
    def forward_rows(cost, t_ids):
        neg, idx = lax.top_k(-cost.T, kk)
        worst = float((-neg[:, -1]).max().item())  # SEED: jax-purity
        return idx, worst

    return jax.jit(forward_rows)


@lru_cache(maxsize=32)
def build_repair_enter(tile, n_tiles):
    def enter_scan(cost, thresh):
        def step(_, t0):
            block = lax.dynamic_slice_in_dim(cost, t0, tile, axis=1)
            hit = np.asarray(block) <= thresh  # SEED: jax-purity
            return None, hit.any(axis=0)

        _, enter = lax.scan(
            step, None, np.arange(n_tiles, dtype=np.int32) * tile
        )
        return enter

    return jax.jit(enter_scan)


@lru_cache(maxsize=32)
def build_repair_reverse_sharded(mesh, r):
    def reverse_pools(pool_c, pool_t):
        keep = pool_c.tolist()[:r]  # SEED: jax-purity
        return pool_t, keep

    return jax.jit(
        shard_map(reverse_pools, mesh=mesh, in_specs=(), out_specs=())
    )
