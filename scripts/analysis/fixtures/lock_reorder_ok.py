"""Clean twin of lock_reorder_bad.py: the same shapes in SPEC order
(shard -> budget leaf), so the analyzer must stay silent."""

import threading


class SessionStore:
    def __init__(self):
        self._lock = threading.Lock()
        self._on_evict = None

    def evict(self, sid):
        with self._lock:
            self._let_go_locked(sid)

    def _let_go_locked(self, sid):
        if self._on_evict is not None:
            self._on_evict(sid, "pressure")


class SessionFabric:
    def __init__(self):
        self._budget_lock = threading.Lock()
        self.shards = [SessionStore()]

    def _on_store_evict(self, session, reason):
        # the real callback shape: shard lock held by the caller, only
        # the budget LEAF taken here
        with self._budget_lock:
            pass

    def pressure_forward(self, shard):
        shard.evict("sid")
        with self._budget_lock:
            pass
