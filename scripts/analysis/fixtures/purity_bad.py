"""Seeded JAX purity violations (the seeded marker lines are the
oracle): the HOST-SYNC-IN-JIT mutation class plus each of the other
purity rules — ambient clock/RNG, Python branching on traced values,
float64-defaulting numpy constructors, and an interprocedural host sync
reached through a helper."""

import time

import jax
import numpy as np


@jax.jit
def bad_item(cost):
    total = cost.sum()
    return float(total.item())  # SEED: jax-purity


@jax.jit
def bad_host_asarray(cost):
    return np.asarray(cost)  # SEED: jax-purity


@jax.jit
def bad_clock(cost):
    return cost * time.time()  # SEED: jax-purity


@jax.jit
def bad_rng(cost):
    return cost + np.random.rand(3)  # SEED: jax-purity


@jax.jit
def bad_branch(cost, eps):
    if eps > 0:  # SEED: jax-purity
        cost = cost / eps
    return cost


@jax.jit
def bad_promote(cost):
    return cost + np.zeros(4)  # SEED: jax-purity


def helper_sync(x):
    return x.tolist()  # SEED: jax-purity


@jax.jit
def bad_via_helper(cost):
    return helper_sync(cost)


@jax.jit
def bad_sync_in_loop(cost):
    out = []
    for _ in range(2):
        out.append(cost.item())  # SEED: jax-purity
    return out


@jax.jit
def bad_branch_in_try(cost, eps):
    try:
        if eps > 0:  # SEED: jax-purity
            cost = cost / eps
    finally:
        pass
    return cost
