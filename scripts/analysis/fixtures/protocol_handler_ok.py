"""Clean twin of protocol_handler_bad.py: the lifecycle implemented to
spec — hardened decode, deadline before mutation, cursor/CRC advance
and flush before ack, marker-carrying refusals. The checker must stay
silent."""


class GoodServicer:
    def assign_delta(self, request, context, session):
        if not self.admission.admit("t"):
            return pb.AssignDeltaResponse(
                session_ok=False,
                error="RESOURCE_EXHAUSTED: tenant over admission rate",
            )
        found, reason = self.sessions.get(request.session_id, request.fp)
        if found is None:
            return pb.AssignDeltaResponse(session_ok=False, error=reason)
        with session.lock:
            if session.evicted:
                return pb.AssignDeltaResponse(
                    session_ok=False, error="session evicted"
                )
            try:
                rows = unblob(request.provider_rows, None)
            except ValueError as e:
                return pb.AssignDeltaResponse(
                    session_ok=False, error=str(e)
                )
            if int(request.tick) != session.tick + 1:
                return pb.AssignDeltaResponse(
                    session_ok=False,
                    error=f"tick cursor mismatch (have {session.tick})",
                )
            self._check_deadline(context, "delta")
            session.apply_delta(rows, {}, rows, {})
            session.tick += 1
            session.last_delta_crc = 11
            self.ckpt.flush_locked(session)
            return pb.AssignDeltaResponse(session_ok=True)
