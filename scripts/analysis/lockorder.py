"""Interprocedural lock-order analysis over the whole package.

The per-file lock-discipline lint answers "is this guarded field read
under *a* lock?"; it is structurally blind to the bug class PR 8/9 shipped
— code that holds the RIGHT lock while acquiring another one in the
WRONG order. This pass builds the whole-program lock-order graph:

  1. Every function body is walked lexically, tracking the multiset of
     lock DOMAINS held at each point (``with <lock>`` items, in item
     order; bare ``.acquire()``/``.release()`` pairs tracked linearly
     through the statement list — the test-harness idiom).
  2. Lock expressions classify to domains through the committed spec
     (``lock_order.toml`` ``[classify]``/``[classify_class]``); a
     lock-shaped expression the spec cannot name is itself a finding —
     the spec must stay total over the tree.
  3. Call sites resolve through :mod:`scripts.analysis.callgraph`
     (including the ``*_locked`` helpers and the spec's callback
     bindings), and a fixpoint computes each function's transitive
     acquisition summary — so "holds shard, calls a helper three frames
     above a budget-lock acquire" produces the shard->budget edge at the
     *call site*.
  4. Every edge (held-domain -> acquired-domain) must be strictly
     rank-ascending per the spec; equal ranks never nest (shard/session
     self-nesting), reentrant domains may re-enter. The aggregate graph
     is also cycle-checked — belt and braces over the rank table itself.

Two further rules ride the same walk:

  * ``lock-dropped``: a ``*_locked``-suffixed helper (the repo's
    called-under-lock naming contract) invoked on a path where the
    caller provably holds nothing — the "dropped lock" bug class.
  * ``lock-unclassified``: a with-item/acquire on a lock-shaped
    expression the spec has no domain for.

Findings use the lint engine's Finding shape; escapes:
``# lint: lock-order-ok`` on the acquisition/call line.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Optional

from scripts.analysis.callgraph import (
    FunctionInfo,
    Index,
    receiver_pattern,
)
from scripts.analysis.spec import Spec, load_spec
from scripts.lints.base import Finding

RULE = "lock-order"
SUPPRESS = "lock-order-ok"

DEFAULT_ROOTS = ("protocol_tpu",)

# functions that run before the object is shared: lock acquisition
# inside them cannot order against anything
EXEMPT_FUNCS = {"__init__", "__post_init__"}


@dataclasses.dataclass(frozen=True)
class Edge:
    held: str
    acquired: str
    rel: str
    line: int
    via: str  # "acquire" or the callee qname for propagated edges


def _is_lock_shaped(expr: ast.AST) -> bool:
    """with-item / receiver shapes that denote a lock object. Calls are
    NOT unwrapped: ``threading.Lock()`` is a constructor and
    ``_tracer.span(...)`` a context manager — acquisition is only ever
    spelled as a bare name or attribute here."""
    if isinstance(expr, ast.Attribute):
        return "lock" in expr.attr.lower()
    if isinstance(expr, ast.Name):
        return "lock" in expr.id.lower()
    return False


def _lock_attr_name(expr: ast.AST) -> str:
    return expr.attr if isinstance(expr, ast.Attribute) else expr.id


class _FunctionScan:
    """One function's lexical walk: acquisition events, call events, and
    the held-domain stack at each."""

    def __init__(self, info: FunctionInfo, analyzer: "LockOrderAnalyzer"):
        self.info = info
        self.an = analyzer
        self.acquires: list = []  # (held tuple, domain, node)
        self.calls: list = []  # (held tuple, call node)
        self.unclassified: list = []  # lock-shaped but spec-less

    def scan(self) -> None:
        node = self.info.node
        body = getattr(node, "body", None)
        if body is None:
            return
        self._block(body, [])

    # ---- classification ----

    def _domain_of(self, expr: ast.AST) -> Optional[str]:
        attr = _lock_attr_name(expr)
        # module-scoped override first (locks touched from module-level
        # closures where no class context exists)
        dom = self.an.spec.classify_class.get(f"{self.info.rel}:{attr}")
        if dom is not None:
            return dom
        class_ctx: Optional[str] = None
        if isinstance(expr, ast.Attribute):
            pattern = receiver_pattern(expr.value)
            if pattern in ("self", "cls"):
                class_ctx = self.info.class_name
            else:
                class_ctx = self.an.spec.receivers.get(pattern)
        return self.an.spec.domain_of(attr, class_ctx)

    # ---- lexical walk ----

    def _block(self, stmts, held: list) -> None:
        # a linear pass so bare .acquire()/.release() extend the held
        # set for the *following* statements of the same block
        local_held = list(held)
        for st in stmts:
            self._stmt(st, local_held)

    def _stmt(self, st: ast.AST, held: list) -> None:
        if isinstance(st, (ast.With, ast.AsyncWith)):
            inner = list(held)
            for item in st.items:
                ctx = item.context_expr
                if _is_lock_shaped(ctx):
                    self._acquire(ctx, inner)
                else:
                    self._exprs(ctx, inner)
            self._block(st.body, inner)
            return
        if isinstance(
            st, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            return  # nested defs run later, with their own entry state
        if isinstance(st, ast.Try):
            self._block(st.body, held)
            for h in st.handlers:
                self._block(h.body, held)
            self._block(st.orelse, held)
            self._block(st.finalbody, held)
            return
        if isinstance(st, (ast.If, ast.While)):
            self._exprs(st.test, held)
            self._block(st.body, held)
            self._block(st.orelse, held)
            return
        if isinstance(st, (ast.For, ast.AsyncFor)):
            self._exprs(st.iter, held)
            self._block(st.body, held)
            self._block(st.orelse, held)
            return
        # expression statements / assigns / returns: look for bare
        # acquire/release and ordinary calls
        self._exprs(st, held, allow_acquire=True)

    def _exprs(self, node: ast.AST, held: list, allow_acquire=False) -> None:
        # manual traversal so nested defs/lambdas are PRUNED (their
        # bodies run later, with their own entry state), unlike ast.walk
        stack = [node]
        while stack:
            sub = stack.pop()
            if isinstance(
                sub, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue  # deferred execution: separate entry state
            stack.extend(ast.iter_child_nodes(sub))
            if not isinstance(sub, ast.Call):
                continue
            fn = sub.func
            if (
                isinstance(fn, ast.Attribute)
                and fn.attr in ("acquire", "release")
                and _is_lock_shaped(fn.value)
            ):
                if fn.attr == "acquire" and allow_acquire:
                    dom = self._acquire(fn.value, held, push=True)
                    del dom
                elif fn.attr == "release":
                    dom = self._domain_of(fn.value)
                    if dom is not None and dom in held:
                        # linear model: release drops the most recent
                        for i in range(len(held) - 1, -1, -1):
                            if held[i] == dom:
                                del held[i]
                                break
                continue
            self.calls.append((tuple(held), sub))

    def _acquire(self, expr, held: list, push: bool = True):
        dom = self._domain_of(expr)
        if dom is None:
            self.unclassified.append(expr)
            return None
        self.acquires.append((tuple(held), dom, expr))
        if push:
            held.append(dom)
        return dom


class LockOrderAnalyzer:
    def __init__(
        self, roots=DEFAULT_ROOTS, spec: Optional[Spec] = None,
        index: Optional[Index] = None,
    ):
        self.spec = spec if spec is not None else load_spec()
        self.index = (
            index if index is not None
            else Index.build(
                roots, spec=self.spec, skip_files=self.spec.skip_files
            )
        )
        self.scans: dict[str, _FunctionScan] = {}
        self.edges: list[Edge] = []
        self.findings: list[Finding] = []
        self.consumed: set = set()  # (rel, line) escapes that fired
        self._line_cache: dict[str, list] = {}

    # ---------------- pipeline ----------------

    def run(self) -> list[Finding]:
        for qname, info in self.index.functions.items():
            scan = _FunctionScan(info, self)
            scan.scan()
            self.scans[qname] = scan
            for expr in scan.unclassified:
                self._find(
                    info, expr, RULE,
                    f"lock-shaped expression "
                    f"{ast.unparse(expr)!r} has no domain in "
                    "lock_order.toml — the spec must stay total",
                )
        summaries = self._fixpoint()
        self._emit_edges(summaries)
        self._check_edges()
        self._check_dropped(summaries)
        self._check_cycles()
        return self.findings

    # ---------------- summaries ----------------

    def _fixpoint(self) -> dict[str, frozenset]:
        """qname -> domains the function may acquire, transitively."""
        summaries = {
            q: frozenset(d for _, d, _ in s.acquires)
            for q, s in self.scans.items()
        }
        changed = True
        while changed:
            changed = False
            for qname, scan in self.scans.items():
                acc = set(summaries[qname])
                before = len(acc)
                for _, call in scan.calls:
                    for callee in self.index.resolve_call(
                        call, scan.info
                    ):
                        acc |= summaries.get(callee, frozenset())
                if len(acc) != before:
                    summaries[qname] = frozenset(acc)
                    changed = True
        return summaries

    def _emit_edges(self, summaries) -> None:
        for qname, scan in self.scans.items():
            info = scan.info
            if info.name in EXEMPT_FUNCS:
                continue
            for held, dom, node in scan.acquires:
                for h in held:
                    self.edges.append(Edge(
                        h, dom, info.rel, node.lineno, "acquire"
                    ))
            for held, call in scan.calls:
                if not held:
                    continue
                for callee in self.index.resolve_call(call, info):
                    for dom in summaries.get(callee, ()):
                        for h in held:
                            self.edges.append(Edge(
                                h, dom, info.rel, call.lineno, callee
                            ))

    # ---------------- checks ----------------

    def _check_edges(self) -> None:
        ranks = self.spec.ranks
        reentrant = set(self.spec.reentrant)
        seen = set()
        for e in self.edges:
            key = (e.held, e.acquired, e.rel, e.line)
            if key in seen:
                continue
            seen.add(key)
            if e.held == e.acquired:
                if e.acquired in reentrant:
                    continue
                why = (
                    f"domain {e.acquired!r} nests itself "
                    f"({'direct' if e.via == 'acquire' else 'via ' + e.via})"
                    " — these locks never nest"
                )
            elif ranks.get(e.acquired, 0) > ranks.get(e.held, 0):
                continue
            else:
                why = (
                    f"acquires {e.acquired!r} "
                    f"(rank {ranks.get(e.acquired, 0)}) while holding "
                    f"{e.held!r} (rank {ranks.get(e.held, 0)})"
                    + (
                        "" if e.via == "acquire"
                        else f" via {e.via}"
                    )
                    + " — violates the committed order "
                    "(scripts/analysis/lock_order.toml)"
                )
            self._find_at(e.rel, e.line, RULE, why)

    def _check_dropped(self, summaries) -> None:
        """A ``*_locked`` helper reached with nothing held: the caller
        dropped the lock the naming contract promises."""
        for qname, scan in self.scans.items():
            info = scan.info
            if (
                info.name.endswith("_locked")
                or info.name in EXEMPT_FUNCS
            ):
                continue  # the contract passes through / not yet shared
            for held, call in scan.calls:
                if held:
                    continue
                fn = call.func
                callee_name = (
                    fn.attr if isinstance(fn, ast.Attribute)
                    else fn.id if isinstance(fn, ast.Name) else ""
                )
                if not callee_name.endswith("_locked"):
                    continue
                self._find_at(
                    info.rel, call.lineno, RULE,
                    f"{callee_name}() called with no lock held — the "
                    "_locked suffix is the called-under-lock contract",
                )

    def _check_cycles(self) -> None:
        graph: dict[str, set] = {}
        site: dict[tuple, Edge] = {}
        for e in self.edges:
            if e.held != e.acquired:
                graph.setdefault(e.held, set()).add(e.acquired)
                site.setdefault((e.held, e.acquired), e)
        # iterative DFS cycle detection over the domain graph
        WHITE, GRAY, BLACK = 0, 1, 2
        color = {d: WHITE for d in graph}
        stack_path: list[str] = []

        def dfs(start: str) -> Optional[list]:
            stack = [(start, iter(graph.get(start, ())))]
            color[start] = GRAY
            stack_path.append(start)
            while stack:
                node, it = stack[-1]
                advanced = False
                for nxt in it:
                    c = color.get(nxt, WHITE)
                    if c == GRAY:
                        return stack_path[stack_path.index(nxt):] + [nxt]
                    if c == WHITE:
                        color[nxt] = GRAY
                        stack_path.append(nxt)
                        stack.append((nxt, iter(graph.get(nxt, ()))))
                        advanced = True
                        break
                if not advanced:
                    stack.pop()
                    stack_path.pop()
                    color[node] = BLACK
            return None

        for d in sorted(graph):
            if color.get(d, 0) == WHITE:
                cyc = dfs(d)
                if cyc:
                    e = site.get((cyc[0], cyc[1]))
                    self._find_at(
                        e.rel if e else "scripts/analysis/lock_order.toml",
                        e.line if e else 0, RULE,
                        "lock-order CYCLE (potential deadlock): "
                        + " -> ".join(cyc),
                    )
                    return

    # ---------------- reporting ----------------

    def _find(self, info: FunctionInfo, node, rule, msg) -> None:
        self._find_at(info.rel, getattr(node, "lineno", 0), rule, msg)

    def _find_at(self, rel: str, line: int, rule: str, msg: str) -> None:
        if self._suppressed(rel, line):
            return
        self.findings.append(Finding(rule, rel, line, msg))

    def _suppressed(self, rel: str, line: int) -> bool:
        tree_lines = self._lines(rel)
        if tree_lines and 1 <= line <= len(tree_lines):
            # own token only: blanket "lint: ok" stays a lint-engine
            # concept (its audit owns that token's staleness)
            if f"lint: {SUPPRESS}" in tree_lines[line - 1]:
                self.consumed.add((rel, line))
                return True
        return False

    def _lines(self, rel: str):
        if rel not in self._line_cache:
            from scripts.lints.base import REPO

            try:
                self._line_cache[rel] = (
                    (REPO / rel).read_text().splitlines()
                )
            except OSError:
                self._line_cache[rel] = []
        return self._line_cache[rel]

    # ---------------- reporting helpers for the CLI ----------------

    def graph_lines(self) -> list[str]:
        """Deduplicated ``held -> acquired`` edges with one example
        site each — the committed graph the docs cite."""
        best: dict[tuple, Edge] = {}
        for e in self.edges:
            best.setdefault((e.held, e.acquired), e)
        out = []
        for (h, a), e in sorted(best.items()):
            out.append(f"{h:12s} -> {a:12s}  ({e.rel}:{e.line})")
        return out


def run(roots=DEFAULT_ROOTS, spec=None, index=None) -> list[Finding]:
    return LockOrderAnalyzer(roots, spec=spec, index=index).run()
