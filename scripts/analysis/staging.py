"""Retrace-hazard pass over the jit staging layer (ISSUE 19 tentpole).

The warm-path economics of the jax engine (PRs 17-18) rest on one
property the purity pass does not check: a warm tick must hit the
compiled cache, never the tracer. Three hazard classes break that
property without breaking correctness — which is why they survive
end-to-end tests and only surface as a 9.5s compile stall per tick on
real hardware:

  R1 static-miss: a jit entry parameter that is a plain Python value
     (annotated ``int``/``bool``/``str``) but NOT covered by
     static_argnames. JAX hashes such a value into the trace as a
     weakly-typed scalar — booleans and strings fail outright, ints
     silently retrace wherever they feed shapes or Python branches.
     Union-annotated parameters (``float | jax.Array``) are the
     sanctioned traced-scalar idiom and are not flagged.

  R2 mutable-capture: a jit-reachable function closes over module- or
     builder-level MUTABLE host state (a dict/list/set binding, or a
     module global rebound via ``global``). The trace freezes the value
     at compile time; every later mutation is silently invisible to the
     compiled executable — the staging twin of the purity pass's
     "traced once, frozen forever" ambient-state rule.

  R3 polymorphic compile key: a call site feeds a compile key — a
     static argname of a jit entry, or any argument of an lru_cached
     jit BUILDER — with a data-dependent count (``flatnonzero(...)
     .size``, ``int(jnp.sum(...))``): a fresh executable per distinct
     churn count, i.e. a recompile per tick. The sanctioned escape
     hatches are the committed quantizers (``[quantizers]`` in
     spmd_spec.toml: _pow2_pad / _pow2_bucket / pick_tile /
     pad_to_multiple) and the inline ``x *= 2`` doubling ladder
     (ops/sparse._greedy_cleanup's budget bucket). Shape-derived
     values (``arr.shape[0]``) are NOT flagged: array shapes are
     already part of the cache key, so a shape-derived static adds no
     recompile the shapes did not.

Entry discovery is shared with the purity pass (decorator form plus the
call-form lru_cached-builder idiom); the quantizer table rides the
committed ``spmd_spec.toml`` so the retrace pass and the shard_map
contract pass can never drift apart. The dynamic twin is
``protocol_tpu/utils/jitwitness.py``: what this pass proves statically,
the witness counts live and ``perf_gate --jax`` gates on. Escape:
``# lint: retrace-ok`` on the line, for hazards that are genuinely
bounded (staleness-audited like every other token).
"""

from __future__ import annotations

import ast
import builtins
from typing import Optional

from scripts.analysis import purity
from scripts.analysis.callgraph import Index, receiver_pattern
from scripts.analysis.spmd import load_spmd_spec
from scripts.lints.base import Finding, REPO

RULE = "jax-retrace"
SUPPRESS = "retrace-ok"

DEFAULT_ROOTS = purity.DEFAULT_ROOTS

STATIC_ANNOTATIONS = {"int", "bool", "str"}
MUTABLE_CTORS = {
    "list", "dict", "set", "bytearray", "deque", "defaultdict",
    "OrderedDict", "Counter",
}
# calls whose result is an index set sized by DATA, not by shape
CHURNY_SOURCES = {"flatnonzero", "nonzero", "argwhere", "unique", "union1d",
                  "setdiff1d", "intersect1d"}
REDUCTIONS = {"sum", "max", "min", "item", "count_nonzero"}
# extractors whose result is structural, not sized-by-data: a pytree's
# treedef is the same for every churn chunk gathered into it
STRUCTURAL = {"structure", "tree_structure", "treedef"}
_BUILTINS = frozenset(dir(builtins))


def _param_list(fn: ast.AST) -> list:
    a = fn.args
    return [
        p for p in (
            list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
        )
        if p.arg not in ("self", "cls")
    ]


def _static_names(fn: ast.AST, raw: tuple) -> set:
    """static_argnames plus static_argnums translated to names."""
    params = _param_list(fn)
    out = set()
    for s in raw:
        if isinstance(s, int):
            if 0 <= s < len(params):
                out.add(params[s].arg)
        else:
            out.add(s)
    return out


class StagingChecker:
    def __init__(
        self, roots=DEFAULT_ROOTS, index: Optional[Index] = None,
        spec=None,
    ):
        self.index = index if index is not None else Index.build(roots)
        self.spec = spec if spec is not None else load_spmd_spec()
        self.purity = purity.PurityChecker(roots, index=self.index)
        self.findings: list[Finding] = []
        self.consumed: set = set()
        self._lines: dict[str, list] = {}

    # ---------------- driver ----------------

    def run(self) -> list[Finding]:
        entries = self.purity.jit_entries()
        reach = self.purity.closure(entries)
        for qname in sorted(entries):
            info = self.index.functions[qname]
            statics = _static_names(info.node, entries[qname])
            self._check_static_miss(info, statics)
        for qname in sorted(reach):
            self._check_mutable_capture(self.index.functions[qname])
        builders = self._builders(entries)
        for info in self.index.functions.values():
            self._check_call_sites(info, entries, builders)
        return self.findings

    # ---------------- R1: static-miss ----------------

    def _check_static_miss(self, info, statics: set) -> None:
        for p in _param_list(info.node):
            ann = p.annotation
            if not (
                isinstance(ann, ast.Name)
                and ann.id in STATIC_ANNOTATIONS
            ):
                continue
            if p.arg in statics:
                continue
            self._find(
                info.rel, p,
                f"jit entry '{info.name}' takes Python "
                f"{ann.id} '{p.arg}' outside static_argnames — "
                "retraces per value (or fails to trace); declare it "
                "static or make it a traced array",
            )

    # ---------------- R2: mutable captures ----------------

    def _check_mutable_capture(self, info) -> None:
        fn = info.node
        bound = {p.arg for p in _param_list(fn)} | {"self", "cls"}
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Name) and isinstance(
                sub.ctx, (ast.Store, ast.Del)
            ):
                bound.add(sub.id)
            elif isinstance(
                sub, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                bound.add(sub.name)
                if sub is not fn:
                    for p in _param_list(sub):
                        bound.add(p.arg)
            elif isinstance(sub, (ast.Import, ast.ImportFrom)):
                for alias in sub.names:
                    bound.add((alias.asname or alias.name).split(".")[0])
            elif isinstance(sub, ast.ExceptHandler) and sub.name:
                bound.add(sub.name)
        seen: set = set()
        for sub in ast.walk(fn):
            if not (
                isinstance(sub, ast.Name)
                and isinstance(sub.ctx, ast.Load)
            ):
                continue
            name = sub.id
            if name in bound or name in seen or name in _BUILTINS:
                continue
            seen.add(name)
            how = self._mutable_binding(info, name)
            if how:
                self._find(
                    info.rel, sub,
                    f"jit-reachable '{info.name}' captures mutable "
                    f"host state '{name}' ({how}) — frozen at trace "
                    "time, later mutations are invisible to the "
                    "compiled executable",
                )

    def _mutable_binding(self, info, name: str) -> Optional[str]:
        """How ``name`` resolves to a MUTABLE binding in the enclosing
        module (or enclosing builder scope for nested entries); None if
        the binding is immutable/unknown (the MAY-not direction)."""
        tree = self.index.trees.get(info.rel)
        if tree is None:
            return None
        # enclosing function scopes of a nested entry first
        qual = info.qname.split("::", 1)[1]
        parts = qual.split(".<locals>.")
        for depth in range(len(parts) - 1, 0, -1):
            anc = f"{info.rel}::" + ".<locals>.".join(parts[:depth])
            anc_info = self.index.functions.get(anc)
            if anc_info is None:
                continue
            kind = _mutable_assign_in(anc_info.node.body, name)
            if kind:
                return f"{kind} in enclosing '{anc_info.name}'"
            if _assigned_in(anc_info.node.body, name):
                return None  # bound, immutably, closer than module scope
        kind = _mutable_assign_in(tree.body, name)
        if kind:
            return f"module-level {kind}"
        for node in ast.walk(tree):
            if isinstance(node, ast.Global) and name in node.names:
                return "rebound via 'global'"
        return None

    # ---------------- R3: polymorphic compile keys ----------------

    def _builders(self, entries) -> dict:
        """qname -> FunctionInfo for every function that BUILDS a jit
        object (a call-form entry is nested inside it, the lru_cache
        idiom): all of its arguments are compile keys."""
        out = {}
        for e in entries:
            if ".<locals>." not in e:
                continue
            rel, qual = e.split("::", 1)
            outer = f"{rel}::{qual.rsplit('.<locals>.', 1)[0]}"
            info = self.index.functions.get(outer)
            if info is not None:
                out[outer] = info
        return out

    def _check_call_sites(self, info, entries, builders) -> None:
        churny = _ChurnTaint(self.spec.quantizers)
        for st in _ordered(info.node):
            churny.observe(st)
            if not isinstance(st, ast.Call):
                continue
            for callee in self.index.resolve_call(st, info):
                if callee in builders:
                    keys = [
                        (a, None) for a in list(st.args)
                        + [kw.value for kw in st.keywords]
                    ]
                elif callee in entries:
                    target = self.index.functions[callee]
                    statics = _static_names(
                        target.node, entries[callee]
                    )
                    keys = _static_args_at_call(
                        st, target.node, statics
                    )
                else:
                    continue
                for expr, argname in keys:
                    if churny.is_churny(expr):
                        what = (
                            f"static '{argname}'" if argname
                            else "builder compile key"
                        )
                        self._find(
                            info.rel, st,
                            f"{what} of '{self.index.functions[callee].name}' "
                            "derives from a data-dependent count — a "
                            "fresh executable per churn set (recompile "
                            "per tick); pad it through a committed "
                            "quantizer (_pow2_pad / _pow2_bucket / "
                            "pick_tile) or a *=2 ladder",
                        )
                        break
                    if _dtype_polymorphic(expr, churny):
                        self._find(
                            info.rel, st,
                            "dtype-polymorphic argument to "
                            f"'{self.index.functions[callee].name}' — a "
                            "conditional dtype forks the jit cache per "
                            "branch; pick one wire dtype",
                        )
                        break

    # ---------------- reporting ----------------

    def _find(self, rel: str, node, msg: str) -> None:
        line = getattr(node, "lineno", 0)
        lines = self._file_lines(rel)
        if lines and 1 <= line <= len(lines):
            if f"lint: {SUPPRESS}" in lines[line - 1]:
                self.consumed.add((rel, line))
                return
        f = Finding(RULE, rel, line, msg)
        if f not in self.findings:
            self.findings.append(f)

    def _file_lines(self, rel: str):
        if rel not in self._lines:
            try:
                self._lines[rel] = (REPO / rel).read_text().splitlines()
            except OSError:
                self._lines[rel] = []
        return self._lines[rel]


def _ordered(root: ast.AST):
    """Pre-order, source-order traversal (ast.walk is breadth-first,
    which would observe assignments out of program order)."""
    for child in ast.iter_child_nodes(root):
        yield child
        yield from _ordered(child)


def _assigned_in(stmts, name: str) -> bool:
    for st in stmts:
        if isinstance(st, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (
                st.targets if isinstance(st, ast.Assign) else [st.target]
            )
            for tgt in targets:
                for t in ast.walk(tgt):
                    if isinstance(t, ast.Name) and t.id == name:
                        return True
    return False


def _mutable_assign_in(stmts, name: str) -> Optional[str]:
    """'<kind>' when ``name`` is bound to a mutable container in this
    statement list (one lexical level — nested defs keep their own
    scopes), else None."""
    for st in stmts:
        if not isinstance(st, (ast.Assign, ast.AnnAssign)):
            continue
        targets = (
            st.targets if isinstance(st, ast.Assign) else [st.target]
        )
        if not any(
            isinstance(t, ast.Name) and t.id == name
            for tgt in targets for t in ast.walk(tgt)
        ):
            continue
        v = st.value
        if isinstance(v, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                          ast.DictComp, ast.SetComp)):
            return "a mutable literal"
        if isinstance(v, ast.Call):
            fname = (
                v.func.id if isinstance(v.func, ast.Name)
                else v.func.attr if isinstance(v.func, ast.Attribute)
                else ""
            )
            if fname in MUTABLE_CTORS:
                return f"{fname}() container"
    return None


def _static_args_at_call(
    call: ast.Call, fn: ast.AST, statics: set
) -> list:
    """(expr, param name) for every call argument bound to a static
    argname of the entry."""
    params = [p.arg for p in _param_list(fn)]
    out = []
    for i, a in enumerate(call.args):
        if i < len(params) and params[i] in statics:
            out.append((a, params[i]))
    for kw in call.keywords:
        if kw.arg in statics:
            out.append((kw.value, kw.arg))
    return out


def _dtype_polymorphic(expr: ast.AST, churny) -> bool:
    """``x.astype(a if c else b)`` / ``dtype=<conditional or churny>``
    forks the compile cache by dtype."""
    for sub in ast.walk(expr):
        if not isinstance(sub, ast.Call):
            continue
        cand = []
        if isinstance(sub.func, ast.Attribute) and (
            sub.func.attr == "astype"
        ) and sub.args:
            cand.append(sub.args[0])
        cand.extend(
            kw.value for kw in sub.keywords if kw.arg == "dtype"
        )
        for c in cand:
            if isinstance(c, ast.IfExp) or churny.is_churny(c):
                return True
    return False


class _ChurnTaint:
    """Per-function value-derived-count taint. Names become churny when
    assigned from an index-set builder (flatnonzero/unique/...) or an
    int()-forced reduction; ``.size``/``len()``/``.shape`` of a churny
    name stays churny; a committed quantizer call launders anything;
    ``x *= 2`` is the doubling-ladder idiom and keeps x's state."""

    def __init__(self, quantizers):
        self.quantizers = set(quantizers)
        self.churny: set[str] = set()

    def observe(self, st: ast.AST) -> None:
        if isinstance(st, ast.Assign):
            targets, value = st.targets, st.value
        elif isinstance(st, ast.AnnAssign) and st.value is not None:
            targets, value = [st.target], st.value
        else:
            return
        direct: list[str] = []
        bases: list[str] = []
        for tgt in targets:
            _target_names(tgt, direct, bases)
        if self.is_churny(value):
            self.churny.update(direct)
            self.churny.update(bases)
        else:
            for n in direct:
                self.churny.discard(n)
            # a clean PARTIAL write (x[i] = ...) does not clean x

    def is_churny(self, expr: ast.AST) -> bool:
        return self._walk(expr)

    def _walk(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Call):
            fname = (
                node.func.id if isinstance(node.func, ast.Name)
                else node.func.attr
                if isinstance(node.func, ast.Attribute) else ""
            )
            if fname in self.quantizers or fname in STRUCTURAL:
                return False  # laundered / structural: bounded key set
            if fname in CHURNY_SOURCES:
                return True
            if fname == "int" or fname in REDUCTIONS:
                # int(jnp.sum(...)) / x.sum() forced to a host scalar
                if fname in REDUCTIONS and isinstance(
                    node.func, ast.Attribute
                ):
                    return True
                return any(self._walk(a) for a in node.args) or any(
                    _has_reduction(a) for a in node.args
                )
            if fname == "len":
                return any(self._walk(a) for a in node.args)
        if isinstance(node, ast.Name):
            return node.id in self.churny
        if isinstance(node, ast.Attribute) and node.attr in (
            "size", "shape"
        ):
            # .size/.shape of a churny index set is the churn COUNT;
            # of anything else it is shape-derived and sanctioned
            return self._walk(node.value)
        return any(
            self._walk(c) for c in ast.iter_child_nodes(node)
        )


def _target_names(tgt: ast.AST, direct: list, bases: list) -> None:
    """Names an assignment target BINDS: the name itself, tuple
    elements, or the base container of a subscript/attribute store —
    never the index expressions (``x[i * rt] = v`` binds x, not rt)."""
    if isinstance(tgt, ast.Name):
        direct.append(tgt.id)
    elif isinstance(tgt, (ast.Tuple, ast.List)):
        for e in tgt.elts:
            _target_names(e, direct, bases)
    elif isinstance(tgt, ast.Starred):
        _target_names(tgt.value, direct, bases)
    elif isinstance(tgt, (ast.Subscript, ast.Attribute)):
        node = tgt.value
        while isinstance(node, (ast.Subscript, ast.Attribute)):
            node = node.value
        if isinstance(node, ast.Name):
            bases.append(node.id)


def _has_reduction(expr: ast.AST) -> bool:
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Call) and isinstance(
            sub.func, ast.Attribute
        ) and sub.func.attr in REDUCTIONS:
            return True
    return False


def run(roots=DEFAULT_ROOTS, index=None, spec=None) -> list[Finding]:
    return StagingChecker(roots, index=index, spec=spec).run()
