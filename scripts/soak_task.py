"""Artifact-producing workload for the full-stack soak: writes random
bytes to a temp file and reports them through the TaskBridge socket
(the reference workload contract: docker/taskbridge/bridge.rs messages),
driving the signed-URL upload + IPFS mirror + work submission path."""

import hashlib
import json
import os
import socket

data = os.urandom(2048)
path = f"/tmp/soak_art_{os.getpid()}.bin"
with open(path, "wb") as f:
    f.write(data)
sha = hashlib.sha256(data).hexdigest()

s = socket.socket(socket.AF_UNIX)
s.connect(os.environ["SOCKET_PATH"])
s.sendall(json.dumps({
    "output": {
        "sha256": sha,
        "output_flops": 7,
        "file_name": "out.bin",
        "save_path": path,
    }
}).encode())
s.close()
print(f"soak task wrote {path} sha={sha}")
