"""Project lint engine: machine-checked versions of the contracts the
codebase only documents. See base.py for the framework and the rule
modules for the catalog:

  determinism     no ambient nondeterminism in native/ops solver paths
  lock-discipline session/arena state only under its lock (services)
  dtype-contract  one canonical dtype table across wire/arena/encoding
  dense-alloc     no O(P*T) numpy allocations outside ops/blocked.py
  isa-dispatch    intrinsics confined to the engine's PER-ISA section
                  (every vector path routes through the kIsaOps table)

Run: ``python -m scripts.lints`` (exit 1 on any finding — the clippy
``-D warnings`` discipline of the reference CI, applied to the
invariants clippy cannot see). The engine also AUDITS escape
annotations: a ``# lint: <token>`` that no longer suppresses any
finding is a ``stale-escape`` finding itself. ``--sarif out.json``
emits SARIF 2.1.0 through the emitter shared with the whole-program
analyzer (``python -m scripts.analysis`` — lock-order graph, session-
protocol state machine, jax purity; see scripts/analysis/).
"""

from scripts.lints import (  # noqa: F401
    densealloc, determinism, dtype_contract, isa_dispatch, lockdiscipline,
)
from scripts.lints.base import RULES, Finding, Rule, Source, register, run_rules

__all__ = [
    "RULES", "Finding", "Rule", "Source", "register", "run_rules",
    "determinism", "lockdiscipline", "dtype_contract", "densealloc",
    "isa_dispatch",
]
