"""Lock-discipline rule: session/arena state is touched lock-held only.

The scheduler seam's concurrency story (PR 3) is lock SHARDING: each
``SolveSession`` carries its own ``lock`` guarding its tick cursor,
columns, and arena; the servicer's shared unary arena hides behind
``_unary_arena_lock``; the ``SessionStore`` registry behind its ``_lock``.
Nothing re-checks that at runtime — a refactor that reads
``session.tick`` before taking ``session.lock`` races eviction and ships
a matching nobody can replay. This rule makes the convention mechanical:

  * attribute access to guarded session state (``tick``, ``arena``,
    ``p_cols``, ``r_cols``, ``evicted``, ``last_used``,
    ``delta_rows_total``) or guarded calls (``solve``, ``apply_delta``)
    on a NON-``self`` receiver must sit lexically inside a ``with``
    whose context expression is lock-shaped (an attribute chain ending
    in a name containing "lock"). ``self.X`` inside the owning class is
    the locked region's body — the caller holds the lock by the class's
    documented contract, and call sites are what this rule audits.
  * ``_sessions`` (the store registry) and ``_native_arena`` (the unary
    arena) are guarded on ANY receiver, including ``self``. The fleet
    layer's shard/budget state joins the same set: ``_by_session`` /
    ``_tenant_bytes`` / ``_total_bytes`` / ``_pressure_evictions`` /
    ``_evictions_by_tenant`` (the fabric's arena-budget accounting,
    leaf ``_budget_lock``), ``_tenants`` (admission registry),
    ``_tokens`` (token buckets), and ``_in_use`` / ``_granted`` (the
    fair thread budget's per-tenant books).

Escapes: methods named ``*_locked`` (the repo's called-under-lock naming
convention), ``__init__``/``__post_init__`` (object not yet shared), and
``# lint: unlocked-ok`` on the line for audited exceptions.

Scope: ``protocol_tpu/services/session_store.py``,
``protocol_tpu/services/scheduler_grpc.py`` (where the sharded-lock
protocol lives), the fleet layer (``protocol_tpu/fleet/fabric.py``,
``protocol_tpu/fleet/admission.py``) whose shard and budget state is
only ever mutated under its shard/fleet locks, and the checkpoint
layer (``protocol_tpu/faults/checkpoint.py``) which serializes a
session's tick-consistent state — a flush outside the session lock
would persist a torn tick that a restart then resurrects.
"""

from __future__ import annotations

import ast

from scripts.lints.base import Finding, Rule, Source, register

GUARDED_SESSION_ATTRS = {
    "tick", "arena", "p_cols", "r_cols", "evicted", "last_used",
    "delta_rows_total",
    # resilience plane: the idempotent-retransmit cache and the
    # deadline watchdog's staleness cursors are tick-consistent state —
    # reading them outside the session lock ships a plan from a torn
    # tick
    "last_p4t", "last_delta_crc", "stale_streak", "solve_ewma_ms",
}
GUARDED_SESSION_CALLS = {"solve", "apply_delta"}
GUARDED_ANY_RECEIVER = {
    "_sessions", "_native_arena",
    # fleet fabric budget accounting (leaf _budget_lock)
    "_by_session", "_tenant_bytes", "_total_bytes",
    "_pressure_evictions", "_evictions_by_tenant",
    # admission registry + token buckets + fair-budget books
    "_tenants", "_tokens", "_in_use", "_granted",
}
EXEMPT_FUNCS = {"__init__", "__post_init__"}


def _attr_root(node: ast.Attribute):
    cur = node.value
    while isinstance(cur, ast.Attribute):
        cur = cur.value
    return cur


def _is_lock_expr(expr: ast.AST) -> bool:
    """True for with-items shaped like ``x.lock`` / ``self._lock`` /
    ``self._unary_arena_lock`` (optionally wrapped in a call)."""
    if isinstance(expr, ast.Call):
        expr = expr.func
    if isinstance(expr, ast.Attribute):
        return "lock" in expr.attr.lower()
    if isinstance(expr, ast.Name):
        return "lock" in expr.id.lower()
    return False


@register
class LockDisciplineRule(Rule):
    name = "lock-discipline"
    suppress_token = "unlocked-ok"

    def applies(self, rel: str) -> bool:
        return rel.endswith((
            "session_store.py", "scheduler_grpc.py",
            "fleet/fabric.py", "fleet/admission.py",
            "faults/checkpoint.py",
        ))

    def _inside_lock(self, src: Source, node: ast.AST) -> bool:
        for anc in src.ancestors(node):
            if isinstance(anc, (ast.With, ast.AsyncWith)) and any(
                _is_lock_expr(item.context_expr) for item in anc.items
            ):
                return True
        return False

    def _exempt_scope(self, src: Source, node: ast.AST) -> bool:
        fn = src.enclosing_function(node)
        return fn is not None and (
            fn in EXEMPT_FUNCS or fn.endswith("_locked")
        )

    def check(self, src: Source) -> list[Finding]:
        out: list[Finding] = []
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Attribute):
                continue
            attr = node.attr
            if attr in GUARDED_ANY_RECEIVER:
                guarded, why = True, f"{attr} (guarded on any receiver)"
            elif attr in GUARDED_SESSION_ATTRS or attr in GUARDED_SESSION_CALLS:
                root = _attr_root(node)
                if isinstance(root, ast.Name) and root.id == "self":
                    # the owning class's own body: the caller holds the
                    # lock by contract; this rule audits the call sites
                    continue
                guarded, why = True, f"session state .{attr}"
            else:
                continue
            if guarded and not self._inside_lock(src, node):
                if self._exempt_scope(src, node):
                    continue
                out += self.finding(
                    src, node,
                    f"access to {why} outside a `with <lock>` block "
                    "(annotate `# lint: unlocked-ok` if the lock is held "
                    "by documented contract)",
                )
        return out
