"""Shared SARIF 2.1.0 emitter for the lint engine and the whole-program
analyzer (``python -m scripts.lints --sarif out.json`` /
``python -m scripts.analysis --sarif out.json``).

One emitter, two producers: both tools speak the same Finding shape
(``scripts.lints.base.Finding``), so CI uploads one artifact format and
GitHub code scanning renders every rule — per-file lint or
interprocedural analysis — as inline annotations on the PR diff.
"""

from __future__ import annotations

import json

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)


def to_sarif(
    findings,
    tool_name: str,
    info_uri: str = "",
    rule_help: dict | None = None,
) -> dict:
    """Findings -> one-run SARIF log. ``rule_help`` maps rule id ->
    short description (rendered in the code-scanning rule index)."""
    rule_ids = sorted({f.rule for f in findings})
    rules = [
        {
            "id": rid,
            "shortDescription": {
                "text": (rule_help or {}).get(rid, rid)
            },
        }
        for rid in rule_ids
    ]
    results = [
        {
            "ruleId": f.rule,
            "level": "error",
            "message": {"text": f.message},
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {
                            "uri": f.path.replace("\\", "/"),
                            "uriBaseId": "%SRCROOT%",
                        },
                        "region": {"startLine": max(int(f.line), 1)},
                    }
                }
            ],
        }
        for f in findings
    ]
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": tool_name,
                        "informationUri": info_uri,
                        "rules": rules,
                    }
                },
                "results": results,
            }
        ],
    }


def write_sarif(
    path: str, findings, tool_name: str, rule_help: dict | None = None
) -> None:
    doc = to_sarif(findings, tool_name, rule_help=rule_help)
    with open(path, "w") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")
