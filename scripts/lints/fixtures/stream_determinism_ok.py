"""Clean twin of stream_determinism_bad.py: the cadence-counted,
arrival-ordered spelling the stream engine actually uses — reconcile
decisions from event COUNTS and certified gaps, coalescing by arrival
order (latest-wins), timing only as stats next to results."""

import time


class CountedStream:
    def __init__(self, reconcile_every: int):
        self.reconcile_every = reconcile_every
        self.events = 0

    def should_reconcile(self) -> bool:
        return self.events >= self.reconcile_every

    def pick_coalesce_victim(self, pending: dict):
        # dict order IS arrival order: the last writer per row wins
        for key in pending:
            last = key
        return last

    def dirty_sources(self, sources):
        return sorted(set(sources))

    def measure_apply(self):
        # perf_counter for STATS is allowed in non-strict modules —
        # walls ride next to plans, never into them
        return time.perf_counter()
