"""Clean twin of faults_determinism_bad.py: the seeded hash-draw
spelling the chaos plane actually uses (plan.FaultSchedule) — every
decision a pure function of (seed, salt, site, method, index)."""

import hashlib
import time


class SeededSchedule:
    def __init__(self, seed: int):
        self.seed = seed

    def _frac(self, salt: str, site: str, method: str,
              index: int) -> float:
        digest = hashlib.sha1(
            f"{self.seed}:{salt}:{site}:{method}:{index}".encode()
        ).digest()
        return int.from_bytes(digest[:8], "big") / 2.0 ** 64

    def decide(self, site: str, method: str, index: int):
        drop = self._frac("drop", site, method, index) < 0.05
        order = []
        for m in sorted({"Assign", "AssignDelta"}):
            order.append(m)
        return drop, order

    def measure_injection(self):
        # perf_counter for STATS is allowed in non-strict modules —
        # stats ride next to fault decisions, never into them
        return time.perf_counter()
