"""Seeded violations for the dtype-contract call-site pass: blob/unblob
without an explicit dtype reintroduce silent coercion at the seam."""

import numpy as np

from dtype_helpers import blob, unblob  # fixture-local stand-ins


def decode(msg, arr):
    cols = unblob(msg)  # SEED: dtype-contract
    frame = blob(arr)  # SEED: dtype-contract
    good_cols = unblob(msg, np.int32)
    good_kw = unblob(msg, expect=np.float32)
    good_frame = blob(arr, np.float32)
    good_frame_kw = blob(arr, dtype=np.int32)
    annotated = unblob(msg)  # lint: dtype-ok
    return cols, frame, good_cols, good_kw, good_frame, good_frame_kw, annotated
