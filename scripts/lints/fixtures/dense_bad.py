"""Seeded violations for the dense-alloc rule."""

import numpy as np


def build_cost_plane(P, T, num_providers, n_tasks, t_pad):
    cost = np.zeros((P, T), np.float32)  # SEED: dense-alloc
    mask = np.ones([num_providers, n_tasks], bool)  # SEED: dense-alloc
    bids = np.full((t_pad, num_providers), -1.0)  # SEED: dense-alloc
    scratch = np.empty((P, 4, T), np.float32)  # SEED: dense-alloc
    kw_form = np.zeros(shape=(P, T), dtype=np.float32)  # SEED: dense-alloc
    return cost, mask, bids, scratch, kw_form


def audited_tile(P, T):
    # audited exemption: bounded tile, argued in the escape annotation
    tile = np.zeros((P, T), np.float32)  # lint: dense-ok
    return tile
