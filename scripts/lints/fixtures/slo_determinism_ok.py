"""Clean twin for the strict tick-indexed determinism mode: the SLO
engine shape done right — tick-counted windows, env-driven config,
sorted iteration, no clock anywhere."""

import os
from collections import deque

WINDOWS = ((8, 32, 4.0), (32, 128, 2.0))


def from_env():
    v = os.environ.get("PROTOCOL_TPU_SLO_BUDGET", "").strip()
    return float(v) if v else 0.05


def observe(state, tick, bad):
    bits = state.setdefault("bits", deque(maxlen=128))
    bits.append(1 if bad else 0)
    events = []
    for short, long_w, thresh in WINDOWS:
        if len(bits) < long_w:
            continue
        burn = sum(list(bits)[-short:]) / short / 0.05
        if burn >= thresh:
            events.append({"tick": int(tick), "window": [short, long_w]})
    for key in sorted({"a", "b"}):
        _ = key
    return events
