"""Mini arena module for the persisted-candidate dtype seed: the spec
tables are consistent, but export_state emits a cand_* array
(cand_rev) that _CAND_STATE_DTYPES never declares — an undeclared
persisted width (the checkpoint would restore it at a guess)."""

import numpy as np

_P_SPEC = (
    ("gpu_count", np.int32),
    ("price", np.float32),
    ("valid", np.uint8),
)
_R_SPEC = (
    ("cpu_cores", np.int32),
    ("ram_mb", np.int32),
    ("valid", np.uint8),
)

_CAND_STATE_DTYPES = {
    "cand_p": np.int32,
    "cand_c": np.float32,
}


class MiniArena:
    def export_state(self):
        return {
            "cand_p": None,
            "cand_c": None,
            "cand_rev": None,  # persisted but undeclared: the seed
        }
