"""Clean twin of ckpt_lock_bad.py: every flush holds the session lock
(or is *_locked by the naming contract) before touching the
tick-consistent state it serializes."""


def flush(ckpt, session):
    with session.lock:
        cursor = session.tick
        plan = session.last_p4t
        crc = session.last_delta_crc
        state = session.arena.export_state()
    return cursor, plan, crc, state


def flush_locked(ckpt, session):
    return (
        session.tick,
        session.stale_streak,
        session.solve_ewma_ms,
    )


def audited_peek(session):
    # fresh object, not yet visible to any store: no lock exists yet
    return session.last_p4t  # lint: unlocked-ok (fresh object)
