"""Clean twin of detector_determinism_bad.py: the clock is INJECTED —
every method takes ``now`` from the caller (the monitor thread owns
real time; tests own a virtual clock), and iteration over the process
map is sorted, so two detectors fed the same sample sequence transition
identically."""


class InjectedClockDetector:
    def __init__(self):
        self.last_seen = {}

    def heartbeat(self, proc_id, now):
        self.last_seen[proc_id] = now

    def probe_failed(self, proc_id, now):
        self.last_seen.setdefault(proc_id, now)

    def evaluate(self, now):
        dead = []
        for pid in sorted(self.last_seen):
            if now - self.last_seen[pid] > 3.0:
                dead.append(pid)
        return dead
