"""Mini encoding module for dtype-contract seeds: EncodedProviders
declares extra_col, which the paired wire table does not carry — the
column would vanish at the seam."""

from dataclasses import dataclass

import numpy as np


@dataclass
class EncodedProviders:
    gpu_count: np.ndarray
    price: np.ndarray
    valid: np.ndarray
    extra_col: np.ndarray


@dataclass
class EncodedRequirements:
    cpu_cores: np.ndarray
    ram_mb: np.ndarray
    valid: np.ndarray
