"""Seeded violations for the lock-discipline rule over the checkpoint
layer (shapes mirror faults/checkpoint.py). A flush that reads session
state outside the session lock persists a TORN tick that a restart
then resurrects — including the resilience-plane cursors the rule
newly guards (last_p4t / last_delta_crc / stale_streak /
solve_ewma_ms)."""


def flush(ckpt, session):
    cursor = session.tick  # SEED: lock-discipline
    plan = session.last_p4t  # SEED: lock-discipline
    crc = session.last_delta_crc  # SEED: lock-discipline
    streak = session.stale_streak  # SEED: lock-discipline
    ewma = session.solve_ewma_ms  # SEED: lock-discipline
    state = session.arena.export_state()  # SEED: lock-discipline
    return cursor, plan, crc, streak, ewma, state


def flush_properly(ckpt, session):
    with session.lock:
        return (
            session.tick,
            session.last_p4t,
            session.arena.export_state(),
        )


def flush_tail_locked(ckpt, session):
    # *_locked naming convention: the caller holds session.lock
    return session.tick, session.last_delta_crc
