"""Seeded violations for the determinism rule over the dfleet failure
detector (shapes mirror protocol_tpu/dfleet/detector.py, which runs
under the STRICT no-clock mode). A detector that reads its own clock
makes time-to-detect unreplayable — the injectable ``now`` its caller
supplies is the ONLY time source, so a recorded heartbeat/miss
sequence replays to the identical transition sequence."""

import time


class DriftingDetector:
    def __init__(self):
        self.last_seen = {}

    def heartbeat(self, proc_id):
        self.last_seen[proc_id] = time.monotonic()  # SEED: determinism

    def probe_failed(self, proc_id):
        self.last_seen.setdefault(proc_id, time.time())  # SEED: determinism

    def evaluate(self):
        dead = []
        for pid in {p for p in self.last_seen}:  # SEED: determinism
            if self.last_seen[pid] < 0:
                dead.append(pid)
        return dead
