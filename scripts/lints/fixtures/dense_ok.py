"""Clean twin of dense_bad.py: bounded-shape allocations the dense-alloc
rule must NOT flag."""

import numpy as np


def build_sparse_structures(P, T, k, extra, g_pad):
    cand_p = np.empty((T, k), np.int32)  # [T, k]: k is bounded
    cand_c = np.empty((T, k + extra), np.float32)
    price = np.zeros(P, np.float32)  # 1-D over one population dim
    retired = np.zeros(T, np.uint8)
    group_mask = np.zeros((g_pad, T), bool)  # groups are bounded
    demand = np.zeros((T, 5), np.float32)
    return cand_p, cand_c, price, retired, group_mask, demand
