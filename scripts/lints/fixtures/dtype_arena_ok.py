"""Consistent arena spec: same columns, same order, width-compatible
dtypes (wire bool_ == arena uint8, the documented 1-byte seam)."""

import numpy as np

_P_SPEC = (
    ("gpu_count", np.int32),
    ("price", np.float32),
    ("valid", np.uint8),
)
_R_SPEC = (
    ("cpu_cores", np.int32),
    ("ram_mb", np.int32),
    ("valid", np.uint8),
)
