"""Seeded violations for the determinism rule over the streaming
engine (shapes mirror protocol_tpu/stream/). An event engine that
consults ``random`` or a wall clock for DECISIONS is unreplayable — a
recorded event trace could not reproduce its plans bit-for-bit."""

import random  # SEED: determinism
import time


class DriftingStream:
    def __init__(self):
        self.events = 0

    def should_reconcile(self) -> bool:
        # cadence from a wall clock: two replays of the same trace
        # reconcile at different events
        return (time.time() % 10.0) < 1.0  # SEED: determinism

    def pick_coalesce_victim(self, pending: dict):
        # randomized coalescing changes which event's values win
        return random.choice(list(pending))  # SEED: determinism

    def dirty_sources(self, sources):
        order = []
        for s in {x for x in sources}:  # SEED: determinism
            order.append(s)
        return order
