"""Mini wire module for dtype-contract seeds: R_WIRE_DTYPES lists a
column (ram_mb) the paired arena spec (dtype_arena_bad.py) lacks, and
the paired encoding (dtype_encoding_bad.py) declares a field
(extra_col) this table does not cover."""

import numpy as np

P_WIRE_DTYPES = {
    "gpu_count": np.dtype(np.int32),
    "price": np.dtype(np.float32),
    "valid": np.dtype(np.bool_),
}
R_WIRE_DTYPES = {
    "cpu_cores": np.dtype(np.int32),
    "ram_mb": np.dtype(np.int32),
    "valid": np.dtype(np.bool_),
}
