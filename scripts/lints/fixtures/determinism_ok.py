"""Clean twin of determinism_bad.py: every deterministic spelling the
rule must NOT flag."""

import time

import numpy as np

EDGES = {3, 1, 2}


def solver_order():
    out = []
    for e in sorted(EDGES):  # sorted set iteration: deterministic
        out.append(e)
    for e in sorted({9, 4, 7}):
        out.append(e)
    if 3 in EDGES:  # membership, not iteration
        out.append(3)
    table = {"a": 1, "b": 2}
    for k in table:  # dict iteration is insertion-ordered (py3.7+)
        out.append(table[k])
    for k, v in table.items():
        out.append(v)
    return out


def stats_only():
    t0 = time.perf_counter()  # timing stats never feed results
    rng = np.arange(8)  # np.arange is not np.random
    return time.perf_counter() - t0, rng
