"""Seeded violations for the determinism rule's STRICT tick-indexed
mode (the SLO engine contract): any clock read — not just wall-clock —
and any datetime import is a finding, because burn-rate windows count
ticks and a replayed workload must reproduce the exact alert sequence.
The ``slo_`` filename prefix opts this fixture into strict mode."""

import datetime  # SEED: determinism
import time
from time import perf_counter  # SEED: determinism

WINDOWS = {8, 32}


def observe(tick, bad):
    # base checks still apply in strict modules
    for w in {16, 64}:  # SEED: determinism
        _ = w
    stamp = time.time()  # SEED: determinism
    started = time.perf_counter()  # SEED: determinism
    beat = time.monotonic()  # SEED: determinism
    return stamp, started, beat


def window_edges():
    # allowed elsewhere for stats, a finding here: the alert engine
    # holds no timestamps at all
    return time.perf_counter_ns()  # SEED: determinism
