"""Mini arena module for dtype-contract seeds: price is int32 here but
float32 on the wire (width clash -> pointer-cast corruption), and
_R_SPEC drops the wire's ram_mb column (diff order divergence)."""

import numpy as np

_P_SPEC = (
    ("gpu_count", np.int32),
    ("price", np.int32),
    ("valid", np.uint8),
)
_R_SPEC = (
    ("cpu_cores", np.int32),
    ("valid", np.uint8),
)
