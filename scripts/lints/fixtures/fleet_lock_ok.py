"""Clean twin of fleet_lock_bad.py: every shard/budget access sits
under its lock (or a *_locked contract) — the rule must flag nothing."""

import threading


class Fabric:
    def __init__(self):
        self._budget_lock = threading.Lock()
        self._by_session = {}
        self._tenant_bytes = {}
        self._total_bytes = 0

    def account(self, session, tenant, est):
        with self._budget_lock:
            if session.evicted:
                return
            self._by_session[session.session_id] = (session, tenant, est)
            self._tenant_bytes[tenant] = (
                self._tenant_bytes.get(tenant, 0) + est
            )
            self._total_bytes += est

    def on_evict(self, session, tenant, est):
        with self._budget_lock:
            self._by_session.pop(session.session_id, None)
            self._tenant_bytes[tenant] -= est
            self._total_bytes -= est

    def snapshot(self):
        with self._budget_lock:
            return {
                "total_bytes": self._total_bytes,
                "tenant_bytes": dict(self._tenant_bytes),
            }

    def release_locked(self, sid, tenant, est):
        # caller holds the budget lock by the naming convention
        del self._by_session[sid]
        self._tenant_bytes[tenant] -= est


class Budget:
    def __init__(self):
        self._lock = threading.Lock()
        self._in_use = {}
        self._granted = {}
        self._tokens = 4.0

    def grant(self, tenant, n):
        with self._lock:
            self._in_use[tenant] = self._in_use.get(tenant, 0) + n
            self._granted[tenant] = self._granted.get(tenant, 0) + n

    def take(self):
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False


def admit(registry, tenant):
    with registry._lock:
        return registry._tenants.get(tenant)
