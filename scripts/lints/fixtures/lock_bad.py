"""Seeded violations for the lock-discipline rule (shapes mirror
services/session_store.py + scheduler_grpc.py)."""

import threading


class Store:
    def __init__(self):
        self._lock = threading.Lock()
        self._sessions = {}  # __init__ is exempt: not shared yet
        self._native_arena = None

    def lookup(self, sid):
        return self._sessions.get(sid)  # SEED: lock-discipline

    def lookup_locked(self, sid):
        # *_locked naming convention: caller holds the lock
        return self._sessions.get(sid)

    def lookup_properly(self, sid):
        with self._lock:
            return self._sessions.get(sid)

    def unary_solve(self, ep, er, w):
        arena = self._native_arena  # SEED: lock-discipline
        return arena

    def unary_solve_properly(self, ep, er, w):
        with self._unary_arena_lock:
            return self._native_arena.solve(ep, er, w)


def delta_tick(session, request):
    if session.evicted:  # SEED: lock-discipline
        return None
    cursor = session.tick + 1  # SEED: lock-discipline
    session.apply_delta(request)  # SEED: lock-discipline
    out = session.solve()  # SEED: lock-discipline
    with session.lock:
        if session.evicted:
            return None
        session.apply_delta(request)
        out = session.solve()
        session.tick += 1
    return out, cursor


def annotated_tick(session):
    # audited exemption: single-threaded test harness, lock not needed
    return session.tick  # lint: unlocked-ok
