"""Consistent trace frame-codec tables (paired with dtype_wire_ok.py):
the dtype-contract trace cross-check must come back clean on this pair."""

import numpy as np

P_TRACE_DTYPES = {
    "gpu_count": np.dtype(np.int32),
    "price": np.dtype(np.float32),
    "valid": np.dtype(np.bool_),
}
R_TRACE_DTYPES = {
    "cpu_cores": np.dtype(np.int32),
    "ram_mb": np.dtype(np.int32),
    "valid": np.dtype(np.bool_),
}
