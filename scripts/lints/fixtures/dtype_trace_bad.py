"""Seeded trace-codec drift for the dtype-contract cross-check (paired
with dtype_wire_bad.py): P drifts a column WIDTH (price recorded f64 vs
the wire's f32 — archived frames reinterpret on replay), R drops a
column (ram_mb) the wire table carries."""

import numpy as np

P_TRACE_DTYPES = {
    "gpu_count": np.dtype(np.int32),
    "price": np.dtype(np.float64),
    "valid": np.dtype(np.bool_),
}
R_TRACE_DTYPES = {
    "cpu_cores": np.dtype(np.int32),
    "valid": np.dtype(np.bool_),
}
