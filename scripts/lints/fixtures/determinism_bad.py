"""Seeded violations for the determinism rule. Each line carrying a
seed marker must produce exactly one finding (tests/test_lints.py
asserts the line sets match)."""

import random  # SEED: determinism
import time
import time as clock
from time import time as wall

import numpy as np

EDGES = {3, 1, 2}


def solver_order():
    out = []
    for e in EDGES:
        out.append(e)
    for e in {9, 4, 7}:  # SEED: determinism
        out.append(e)
    for e in set(out):  # SEED: determinism
        out.append(e)
    picked = [e for e in frozenset(out)]  # SEED: determinism
    for k in vars(np):  # SEED: determinism
        _ = k
    return out + picked


def stamped_solve():
    seed = time.time()  # SEED: determinism
    aliased = clock.time_ns()  # SEED: determinism
    from_import = wall()  # SEED: determinism
    jitter = random.random()  # SEED: determinism
    noise = np.random.normal(0.0, 1.0)  # SEED: determinism
    return seed + jitter + noise + aliased + from_import


def escaped_solve():
    # audited exemption: the escape comment must drop the finding
    blessed = time.time()  # lint: determinism-ok
    return blessed
