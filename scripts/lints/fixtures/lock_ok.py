"""Clean twin of lock_bad.py: every locked (or legitimately exempt)
spelling the lock-discipline rule must NOT flag."""

import threading


class Session:
    def __init__(self):
        self.lock = threading.Lock()
        self.tick = 0
        self.arena = None

    def solve(self):
        # self.X inside the owning class: callers hold the lock by the
        # documented contract; the rule audits call sites
        self.tick += 1
        return self.arena


class Store:
    def __init__(self):
        self._lock = threading.Lock()
        self._sessions = {}

    def get(self, sid):
        with self._lock:
            s = self._sessions.get(sid)
            if s is not None:
                s.last_used = 0.0
            return s

    def _expire_locked(self):
        for sid in list(self._sessions):
            self._sessions.pop(sid)


def delta_tick(session, request):
    with session.lock:
        if session.evicted:
            return None
        session.apply_delta(request)
        out = session.solve()
        session.tick += 1
    return out
