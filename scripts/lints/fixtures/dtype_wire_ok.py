"""Consistent wire table (paired with dtype_arena_ok.py /
dtype_encoding_ok.py): the dtype-contract cross-check must come back
clean on this trio."""

import numpy as np

P_WIRE_DTYPES = {
    "gpu_count": np.dtype(np.int32),
    "price": np.dtype(np.float32),
    "valid": np.dtype(np.bool_),
}
R_WIRE_DTYPES = {
    "cpu_cores": np.dtype(np.int32),
    "ram_mb": np.dtype(np.int32),
    "valid": np.dtype(np.bool_),
}
