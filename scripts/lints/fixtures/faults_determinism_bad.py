"""Seeded violations for the determinism rule over the chaos plane
(shapes mirror protocol_tpu/faults/plan.py). A fault schedule that
consults ``random`` or a wall clock is unreplayable — the seeded
byte-replayability claim is the whole point of the plane."""

import random  # SEED: determinism
import time


class DriftingSchedule:
    def __init__(self, seed: int):
        self.seed = seed

    def decide(self, site: str, method: str, index: int):
        drop = random.random() < 0.05  # SEED: determinism
        delay = (time.time() % 1.0) < 0.05  # SEED: determinism
        order = []
        for m in {"Assign", "AssignDelta"}:  # SEED: determinism
            order.append(m)
        return drop, delay, order
