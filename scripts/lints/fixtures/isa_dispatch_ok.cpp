// Clean twin for the isa-dispatch rule: the include carries the audited
// escape, every intrinsic lives inside the delimited section, forward
// DECLARATIONS (no intrinsic tokens) are legal outside it, and the one
// deliberate exemption uses the rule's escape annotation.
#include <cstdint>
#include <immintrin.h>  // lint: isa-dispatch-include

// target-attributed forward declaration: no intrinsic tokens, legal
__attribute__((target("avx2"))) float lane_sum_avx2(const float* x);

// deliberate exemption, escape-annotated (the audit trail): a vector
// TYPE in a sizeof probe — no instruction executes, so it may stay out
static const int kLaneBytes = sizeof(__m256);  // lint: isa-dispatch-ok

// ==== BEGIN PER-ISA KERNELS (isa-dispatch) =================================
__attribute__((target("avx2"))) float lane_sum_avx2(const float* x) {
  __m256 v = _mm256_loadu_ps(x);
  float out[8];
  _mm256_storeu_ps(out, v);
  float acc = 0.0f;
  for (int i = 0; i < 8; ++i) acc += out[i];
  return acc;
}
// ==== END PER-ISA KERNELS (isa-dispatch) ===================================

// entry points route through a dispatch seam, never call lanes directly
typedef float (*sum_fn)(const float*);
static float scalar_sum(const float* x) {
  float acc = 0.0f;
  for (int i = 0; i < 8; ++i) acc += x[i];
  return acc;
}
static const sum_fn kSumOps[2] = {scalar_sum, lane_sum_avx2};
float entry_sum(const float* x, int isa) { return kSumOps[isa](x); }
