// Seeded violations for the isa-dispatch rule: raw intrinsics, vector
// types, and an unescaped immintrin include OUTSIDE the delimited
// PER-ISA section — each seeded line below must be exactly one finding.
#include <cstdint>
#include <immintrin.h>  // SEED: isa-dispatch (include without the audited escape)

// intrinsic call in a plain entry point: executes unconditionally on
// the baseline build (no target attribute) — SIGILL on pre-AVX2 hosts
static float bad_entry_sum(const float* x) {
  return _mm256_cvtss_f32(_mm256_loadu_ps(x));  // SEED: isa-dispatch
}

// vector TYPE leaking outside the section is the same contract break
static __m512 bad_state;  // SEED: isa-dispatch

// gcc builtin spelling of the same escape hatch
static int bad_popcnt(unsigned v) {
  return __builtin_ia32_popcountsi2(v);  // SEED: isa-dispatch
}

// ==== BEGIN PER-ISA KERNELS (isa-dispatch) =================================
__attribute__((target("avx2"))) static float inside_is_fine(const float* x) {
  return _mm256_cvtss_f32(_mm256_loadu_ps(x));
}
// ==== END PER-ISA KERNELS (isa-dispatch) ===================================

// the section does not launder code BELOW it
static float bad_after_section(const float* x) {
  return _mm_cvtss_f32(_mm_load_ss(x));  // SEED: isa-dispatch
}
