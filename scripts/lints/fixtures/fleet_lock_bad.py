"""Seeded violations for the lock-discipline rule's FLEET scope
(shapes mirror fleet/fabric.py + fleet/admission.py: shard/budget state
mutated outside its shard/fleet locks)."""

import threading


class Fabric:
    def __init__(self):
        self._budget_lock = threading.Lock()
        self._by_session = {}  # __init__ is exempt: not shared yet
        self._tenant_bytes = {}
        self._total_bytes = 0

    def account(self, session, tenant, est):
        self._by_session[session.session_id] = (session, tenant, est)  # SEED: lock-discipline
        self._tenant_bytes[tenant] = est  # SEED: lock-discipline
        self._total_bytes += est  # SEED: lock-discipline

    def account_properly(self, session, tenant, est):
        with self._budget_lock:
            self._by_session[session.session_id] = (session, tenant, est)
            self._tenant_bytes[tenant] = est
            self._total_bytes += est

    def release_locked(self, sid, tenant, est):
        # *_locked naming convention: caller holds the budget lock
        del self._by_session[sid]
        self._tenant_bytes[tenant] -= est


class Budget:
    def __init__(self):
        self._lock = threading.Lock()
        self._in_use = {}
        self._granted = {}
        self._tokens = 4.0

    def grant(self, tenant, n):
        self._in_use[tenant] = n  # SEED: lock-discipline
        self._granted[tenant] = n  # SEED: lock-discipline

    def grant_properly(self, tenant, n):
        with self._lock:
            self._in_use[tenant] = self._in_use.get(tenant, 0) + n
            self._granted[tenant] = n

    def take(self):
        if self._tokens >= 1.0:  # SEED: lock-discipline
            return True
        return False

    def take_properly(self):
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False


def admit(registry, tenant):
    entry = registry._tenants.get(tenant)  # SEED: lock-discipline
    with registry._lock:
        entry = registry._tenants.get(tenant)
    return entry


def audited(fabric):
    # audited exemption: single-threaded harness, lock not needed
    return fabric._total_bytes  # lint: unlocked-ok
