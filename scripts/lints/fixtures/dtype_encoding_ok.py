"""Consistent encoding: dataclass fields covered exactly by the paired
wire table."""

from dataclasses import dataclass

import numpy as np


@dataclass
class EncodedProviders:
    gpu_count: np.ndarray
    price: np.ndarray
    valid: np.ndarray


@dataclass
class EncodedRequirements:
    cpu_cores: np.ndarray
    ram_mb: np.ndarray
    valid: np.ndarray
