"""Determinism rule: no ambient nondeterminism in solver paths.

The native/ops kernels promise bit-identical results for every thread
count and every process (PR 1/3's warm chains, the session protocol's
tick parity, the perf gate's thread-invariance floor all rest on it).
Three ambient-nondeterminism classes can silently break that promise:

  * iteration over sets (``for x in {...}`` / ``set(...)`` /
    ``frozenset(...)``): ordering depends on PYTHONHASHSEED, so two
    replicas iterate differently. Wrap in ``sorted(...)`` instead.
    (Dict iteration is insertion-ordered in CPython >= 3.7 and allowed;
    iterating ``vars()``/``globals()``/``locals()`` is not — attribute
    insertion order is an implementation detail of unrelated code.)
  * wall-clock reads (``time.time()`` / ``time.time_ns()``) feeding
    solver state. ``perf_counter`` for *stats* is fine — stats ride next
    to results, never into them.
  * ``random`` / ``np.random`` in kernel code: even seeded generators
    drift across numpy versions; jitter must come from the hash-based
    tie-breakers the kernels already share.

Scope: ``protocol_tpu/native/`` and ``protocol_tpu/ops/``, plus the
decision-quality plane (``protocol_tpu/obs/quality.py``,
``protocol_tpu/obs/slo.py``) whose replay-stability contract is the
same bit-for-bit promise, plus the chaos plane
(``protocol_tpu/faults/``): a fault schedule that consulted ``random``
or a wall clock would make every chaos run unreplayable — the seeded
byte-replayability claim is the whole point of the plane. The dfleet
failure detector (``protocol_tpu/dfleet/detector.py``) joins under
the STRICT no-clock mode: it reads time ONLY through the injectable
``now`` its caller supplies, so a recorded heartbeat/miss sequence
replays to the identical transition sequence — any in-module clock
read (``monotonic``/``perf_counter`` included) breaks that.

The SLO engine (``obs/slo.py``) additionally runs under the STRICT
no-clock mode: its burn-rate windows are TICK-indexed by contract (a
replayed workload must reproduce the exact alert sequence), so ANY
clock read — ``perf_counter`` and ``monotonic`` included, which the
base rule allows for stats — and any ``datetime`` import is a finding.
Wall-clock correlation belongs to the scrape layer, never inside the
alert engine.

Escape: ``# lint: determinism-ok`` on the offending line.
"""

from __future__ import annotations

import ast
import dataclasses

from scripts.lints.base import Finding, Rule, Source, register

_SET_BUILTINS = {"set", "frozenset"}
_NONDET_MAPPINGS = {"vars", "globals", "locals"}
_RANDOM_ROOTS = {"np", "numpy"}


@dataclasses.dataclass(frozen=True)
class Scope:
    """One determinism-covered module family. THE single source of
    truth for (a) the rule's path filter, (b) strict-mode selection,
    and (c) the fixture-harness parametrization in tests/test_lints.py
    — which previously each hardcoded their own directory lists, so a
    new package could land in one and silently fall out of the other."""

    name: str
    prefixes: tuple = ()   # repo-relative directory prefixes
    suffixes: tuple = ()   # exact-module suffixes
    fixture_prefix: str = ""  # "<prefix>determinism_{bad,ok}.py" twins
    strict: bool = False   # strict no-clock mode (tick-indexed modules)


# add a package here and BOTH the rule scope and the seeded-fixture
# harness pick it up (the harness asserts the fixture twins exist)
SCOPES = (
    Scope(
        "kernel",
        prefixes=("protocol_tpu/native/", "protocol_tpu/ops/"),
    ),
    Scope(
        "faults",
        prefixes=("protocol_tpu/faults/",),
        fixture_prefix="faults_",
    ),
    Scope("quality", suffixes=("protocol_tpu/obs/quality.py",)),
    Scope(
        "stream",
        prefixes=("protocol_tpu/stream/",),
        fixture_prefix="stream_",
    ),
    Scope(
        "slo",
        suffixes=("protocol_tpu/obs/slo.py",),
        fixture_prefix="slo_",
        strict=True,
    ),
    Scope(
        "detector",
        suffixes=("protocol_tpu/dfleet/detector.py",),
        fixture_prefix="detector_",
        strict=True,
    ),
)


def _is_set_expr(node: ast.AST) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        return node.func.id in _SET_BUILTINS | _NONDET_MAPPINGS
    return False


@register
class DeterminismRule(Rule):
    name = "determinism"
    suppress_token = "determinism-ok"

    def applies(self, rel: str) -> bool:
        return any(
            rel.startswith(s.prefixes) or rel.endswith(s.suffixes)
            for s in SCOPES
            if s.prefixes or s.suffixes
        )

    @classmethod
    def _is_strict(cls, rel: str) -> bool:
        # strict mode follows the SAME table: the real tick-indexed
        # modules by suffix, their fixture twins by filename prefix
        name = rel.replace("\\", "/").rsplit("/", 1)[-1]
        return any(
            rel.endswith(s.suffixes)
            or (s.fixture_prefix and name.startswith(s.fixture_prefix))
            for s in SCOPES
            if s.strict
        )

    @staticmethod
    def _time_bindings(tree: ast.AST) -> tuple[set[str], set[str]]:
        """(aliases the time MODULE is bound to, local names bound to
        time.time/time_ns themselves) — so `import time as clock` and
        `from time import time` can't dodge the wall-clock check."""
        mod_aliases: set[str] = set()
        fn_names: set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "time":
                        mod_aliases.add(a.asname or a.name)
            elif isinstance(node, ast.ImportFrom) and node.module == "time":
                for a in node.names:
                    if a.name in ("time", "time_ns"):
                        fn_names.add(a.asname or a.name)
        return mod_aliases, fn_names

    def check(self, src: Source) -> list[Finding]:
        out: list[Finding] = []
        self._strict = self._is_strict(src.rel)
        self._time_mods, self._time_fns = self._time_bindings(src.tree)
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.For, ast.AsyncFor)):
                out += self._check_iter(src, node.iter)
            elif isinstance(
                node, (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)
            ):
                for gen in node.generators:
                    out += self._check_iter(src, gen.iter)
            elif isinstance(node, ast.Call):
                out += self._check_call(src, node)
            elif isinstance(node, (ast.Import, ast.ImportFrom)):
                out += self._check_import(src, node)
        return out

    def _check_iter(self, src: Source, it: ast.AST) -> list[Finding]:
        if _is_set_expr(it):
            return self.finding(
                src, it,
                "iteration over an unsorted set/mapping view — hash-order "
                "varies per process; wrap in sorted(...)",
            )
        return []

    def _check_call(self, src: Source, call: ast.Call) -> list[Finding]:
        fn = call.func
        # from time import time [as t]; t()
        if isinstance(fn, ast.Name) and fn.id in self._time_fns:
            return self.finding(
                src, call,
                "wall-clock read in a solver path — results must not "
                "depend on when the solve ran",
            )
        if not isinstance(fn, ast.Attribute):
            return []
        # <any alias of the time module>.time()/.time_ns() — and in the
        # STRICT tick-indexed modules, any clock at all
        if isinstance(fn.value, ast.Name) and fn.value.id in self._time_mods:
            if fn.attr in ("time", "time_ns"):
                return self.finding(
                    src, call,
                    "wall-clock read in a solver path — results must not "
                    "depend on when the solve ran",
                )
            if self._strict:
                return self.finding(
                    src, call,
                    f"time.{fn.attr} in a tick-indexed module — burn-rate "
                    "windows count ticks, never clocks (replay must "
                    "reproduce the exact alert sequence)",
                )
        # random.X(...) / np.random.X(...)
        root = fn.value
        if isinstance(root, ast.Name) and root.id == "random":
            return self.finding(
                src, call, "random module call in a solver path"
            )
        if (
            isinstance(root, ast.Attribute)
            and root.attr == "random"
            and isinstance(root.value, ast.Name)
            and root.value.id in _RANDOM_ROOTS
        ):
            return self.finding(
                src, call,
                "np.random in a solver path — jitter must come from the "
                "shared hash-based tie-breakers",
            )
        return []

    def _check_import(self, src: Source, node: ast.AST) -> list[Finding]:
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "random" or a.name.startswith("random."):
                    return self.finding(
                        src, node, "random import in a solver module"
                    )
                if self._strict and (
                    a.name == "datetime" or a.name.startswith("datetime.")
                ):
                    return self.finding(
                        src, node,
                        "datetime import in a tick-indexed module — the "
                        "alert engine holds no timestamps",
                    )
        elif isinstance(node, ast.ImportFrom):
            if node.module == "random":
                return self.finding(
                    src, node, "random import in a solver module"
                )
            if self._strict and node.module == "time":
                # a from-import would bind the clock to a bare name the
                # call-site check can't see — flag it at the source
                return self.finding(
                    src, node,
                    "time import in a tick-indexed module — burn-rate "
                    "windows count ticks, never clocks",
                )
            if self._strict and node.module and (
                node.module == "datetime"
                or node.module.startswith("datetime.")
            ):
                return self.finding(
                    src, node,
                    "datetime import in a tick-indexed module — the "
                    "alert engine holds no timestamps",
                )
        return []
