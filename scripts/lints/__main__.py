"""CLI for the project lint engine: ``python -m scripts.lints [roots...]``."""

from __future__ import annotations

import argparse
import sys

from scripts.lints import RULES, run_rules
from scripts.lints.base import DEFAULT_ROOTS


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m scripts.lints",
        description="project rule engine (determinism / lock / dtype / "
                    "dense-alloc contracts)",
    )
    ap.add_argument("roots", nargs="*", default=list(DEFAULT_ROOTS),
                    help="files or directories to lint (default: %(default)s)")
    ap.add_argument("--rule", action="append", default=None,
                    help="run only this rule (repeatable)")
    ap.add_argument("--list", action="store_true", help="list rules and exit")
    ap.add_argument("--sarif", default=None, metavar="OUT.json",
                    help="also write findings as SARIF 2.1.0 (the shared "
                         "emitter CI uploads to GitHub code scanning)")
    args = ap.parse_args(argv)

    if args.list:
        for r in RULES:
            print(f"{r.name:16s} escape: # lint: {r.suppress_token}")
        return 0
    rules = None
    if args.rule:
        known = {r.name: r for r in RULES}
        unknown = [n for n in args.rule if n not in known]
        if unknown:
            print(f"unknown rule(s): {unknown}; have {sorted(known)}")
            return 2
        rules = [known[n] for n in args.rule]
    findings = run_rules(roots=args.roots, rules=rules)
    for f in findings:
        print(f)
    if args.sarif:
        from scripts.lints.sarif import write_sarif

        write_sarif(
            args.sarif, findings, "scripts.lints",
            rule_help={r.name: (r.__doc__ or r.name).strip().split("\n")[0]
                       for r in (rules or RULES)},
        )
        print(f"sarif written: {args.sarif} ({len(findings)} finding(s))")
    if not findings:
        names = ", ".join(r.name for r in (rules or RULES))
        print(f"lints clean ({names}) over {', '.join(args.roots)}")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
