"""Dense-allocation rule: no O(P*T) numpy tensors outside ops/blocked.py.

The whole scale story (SCALING.md ladder) is that nothing materializes
the [P, T] plane on the host: candidates are top-K sparse, the wire
ships columns, the arena diffs rows. One careless ``np.zeros((P, T))``
in a 1M x 1M code path is a 4 TB allocation — it OOMs in production
after sailing through every 2k-row test. The blocked JAX kernels
(ops/blocked.py) are the single audited home of dense tiles and are
exempt.

Detection: calls to ``np.zeros/ones/empty/full`` whose shape tuple has
two or more population-scale dimensions — identifier names the codebase
uses for provider/task row counts (``P``, ``T``, ``t_pad``,
``num_providers``, ...). Bounded dims (``k``, ``extra``, group counts)
never match, so [T, k] candidate buffers stay legal.

Escape: ``# lint: dense-ok`` for an audited dense allocation (with the
bound argued in a comment, like blocked.py's tiles).
"""

from __future__ import annotations

import ast

from scripts.lints.base import Finding, Rule, Source, register

_ALLOC_FNS = {"zeros", "ones", "empty", "full"}
_NP_ROOTS = {"np", "numpy"}
# identifiers this codebase uses for population-scale row counts
_POP_DIMS = {
    "P", "T", "Pn", "Pl", "p_pad", "t_pad", "s_pad", "r_pad", "rpad",
    "p_padded", "t_padded", "n_providers", "num_providers", "n_tasks",
    "num_tasks", "n_p", "n_t", "n_real", "P_pad", "T_pad",
}


def _dim_name(node: ast.AST) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return ""


@register
class DenseAllocRule(Rule):
    name = "dense-alloc"
    suppress_token = "dense-ok"

    def applies(self, rel: str) -> bool:
        return (
            rel.startswith("protocol_tpu/")
            and not rel.endswith("ops/blocked.py")
        )

    def check(self, src: Source) -> list[Finding]:
        out: list[Finding] = []
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not (
                isinstance(fn, ast.Attribute)
                and fn.attr in _ALLOC_FNS
                and isinstance(fn.value, ast.Name)
                and fn.value.id in _NP_ROOTS
            ):
                continue
            shape = node.args[0] if node.args else next(
                (kw.value for kw in node.keywords if kw.arg == "shape"), None
            )
            if not isinstance(shape, (ast.Tuple, ast.List)):
                continue
            pop = [d for d in map(_dim_name, shape.elts) if d in _POP_DIMS]
            if len(pop) >= 2:
                out += self.finding(
                    src, node,
                    f"dense np.{fn.attr} over population-scale dims "
                    f"{pop} — O(P*T) host allocations live only in "
                    "ops/blocked.py (4 TB at the 1M ladder)",
                )
        return out
