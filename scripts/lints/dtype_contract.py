"""Dtype-contract rule: one canonical dtype per column, everywhere.

The zero-copy wire (PR 2) rests on a single dtype table: a column rides
``TensorBlob`` frames as raw bytes, is asserted ONCE at decode against
``proto/wire.py``'s ``P_WIRE_DTYPES``/``R_WIRE_DTYPES``, and then flows
unchecked into the arena, whose own ``_P_SPEC``/``_R_SPEC`` drive the
value-based dirty diffing and the C++ engine's pointer casts. Three
places hold that table today; nothing cross-checks them — a new column
added to one with a different width corrupts the seam silently (the C++
side reads raw pointers at the dtype it was told).

This rule makes the contract mechanical:

  * ``P_WIRE_DTYPES``/``R_WIRE_DTYPES`` (wire) and ``_P_SPEC``/``_R_SPEC``
    (arena) must list the SAME columns in the SAME order with
    width-compatible dtypes (``bool_`` on the wire and ``uint8`` in the
    arena are the same byte — the documented numpy<->ctypes seam).
  * the wire specs must cover exactly the ``EncodedProviders`` /
    ``EncodedRequirements`` dataclass fields (ops/encoding.py) — a field
    added to the encoding but not the wire would vanish at the seam.
  * ``P_TRACE_DTYPES``/``R_TRACE_DTYPES`` (the flight-recorder frame
    codec, trace/format.py) must mirror the wire tables exactly: trace
    frames PERSIST on disk, so a drifted column silently reinterprets
    every archived trace at the wrong width on the next replay.
  * every ``blob(...)``/``unblob(...)`` call site must pass an explicit
    dtype (second argument): an un-annotated encode/decode reintroduces
    exactly the silent-coercion class the seam's single-assert design
    removed. Escape: ``# lint: dtype-ok``.

Everything is read via AST — the rule never imports the modules it
audits (ops/encoding.py pulls jax; lint must run on a bare host).
"""

from __future__ import annotations

import ast
import pathlib
from typing import Optional

from scripts.lints.base import REPO, Finding, Rule, Source, register

# 1-byte equivalence across the numpy<->wire<->ctypes seam
_EQUIV = {"bool_": "u1", "bool": "u1", "uint8": "u1"}

_WIRE = "protocol_tpu/proto/wire.py"
_ARENA = "protocol_tpu/native/arena.py"
_ENCODING = "protocol_tpu/ops/encoding.py"
_TRACE = "protocol_tpu/trace/format.py"


def _dtype_name(node: ast.AST) -> Optional[str]:
    """``np.dtype(np.int32)`` / ``np.int32`` / ``"int32"`` -> "int32"."""
    if isinstance(node, ast.Call) and node.args:
        return _dtype_name(node.args[0])
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _canon(name: str) -> str:
    return _EQUIV.get(name, name)


def _dict_spec(tree: ast.AST, var: str) -> Optional[list[tuple[str, str, int]]]:
    """Extract ``VAR = {"col": np.dtype(np.int32), ...}`` as
    [(name, dtype, line)]."""
    for node in ast.walk(tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        else:
            continue
        for t in targets:
            if isinstance(t, ast.Name) and t.id == var and isinstance(value, ast.Dict):
                out = []
                for k, v in zip(value.keys, value.values):
                    if isinstance(k, ast.Constant):
                        out.append((k.value, _dtype_name(v) or "?", k.lineno))
                return out
    return None


def _tuple_spec(tree: ast.AST, var: str) -> Optional[list[tuple[str, str, int]]]:
    """Extract ``VAR = (("col", np.int32), ...)`` as [(name, dtype, line)]."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        for t in node.targets:
            if isinstance(t, ast.Name) and t.id == var and isinstance(
                node.value, (ast.Tuple, ast.List)
            ):
                out = []
                for elt in node.value.elts:
                    if isinstance(elt, (ast.Tuple, ast.List)) and len(elt.elts) == 2:
                        k, v = elt.elts
                        if isinstance(k, ast.Constant):
                            out.append((k.value, _dtype_name(v) or "?", k.lineno))
                return out
    return None


def _dataclass_fields(tree: ast.AST, cls: str) -> Optional[list[str]]:
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == cls:
            return [
                stmt.target.id
                for stmt in node.body
                if isinstance(stmt, ast.AnnAssign)
                and isinstance(stmt.target, ast.Name)
            ]
    return None


@register
class DtypeContractRule(Rule):
    name = "dtype-contract"
    suppress_token = "dtype-ok"

    def __init__(
        self,
        wire: str = _WIRE,
        arena: str = _ARENA,
        encoding: Optional[str] = _ENCODING,
        trace: Optional[str] = _TRACE,
    ):
        self.wire = wire
        self.arena = arena
        self.encoding = encoding
        self.trace = trace

    def applies(self, rel: str) -> bool:
        # call-site pass: anywhere blob/unblob travel
        return rel.startswith("protocol_tpu/")

    # ---------------- per-file: encode/decode call sites ----------------

    def check(self, src: Source) -> list[Finding]:
        out: list[Finding] = []
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            fname = fn.id if isinstance(fn, ast.Name) else (
                fn.attr if isinstance(fn, ast.Attribute) else None
            )
            if fname not in ("blob", "unblob"):
                continue
            has_dtype = len(node.args) >= 2 or any(
                kw.arg in ("dtype", "expect") for kw in node.keywords
            )
            if not has_dtype:
                out += self.finding(
                    src, node,
                    f"{fname}() without an explicit dtype — the seam "
                    "asserts dtypes exactly once, at this boundary",
                )
        return out

    # ---------------- cross-file: the canonical tables ----------------

    def _parse(self, rel: str) -> Optional[ast.AST]:
        path = pathlib.Path(rel)
        if not path.is_absolute():
            path = REPO / rel
        if not path.exists():
            return None
        return ast.parse(path.read_text(), filename=str(path))

    def check_repo(self) -> list[Finding]:
        out: list[Finding] = []
        wire_tree = self._parse(self.wire)
        arena_tree = self._parse(self.arena)
        if wire_tree is None or arena_tree is None:
            return [Finding(
                self.name, self.wire, 0,
                "cannot locate the wire/arena dtype tables to cross-check",
            )]
        enc_tree = self._parse(self.encoding) if self.encoding else None
        for wire_var, arena_var, enc_cls in (
            ("P_WIRE_DTYPES", "_P_SPEC", "EncodedProviders"),
            ("R_WIRE_DTYPES", "_R_SPEC", "EncodedRequirements"),
        ):
            wspec = _dict_spec(wire_tree, wire_var)
            aspec = _tuple_spec(arena_tree, arena_var)
            if wspec is None or aspec is None:
                out.append(Finding(
                    self.name, self.wire if wspec is None else self.arena, 0,
                    f"missing dtype table {wire_var if wspec is None else arena_var}",
                ))
                continue
            wnames = [n for n, _, _ in wspec]
            anames = [n for n, _, _ in aspec]
            if wnames != anames:
                extra_w = [n for n in wnames if n not in anames]
                extra_a = [n for n in anames if n not in wnames]
                detail = (
                    f"wire-only={extra_w} arena-only={extra_a}"
                    if (extra_w or extra_a) else "same columns, different order"
                )
                out.append(Finding(
                    self.name, self.arena,
                    aspec[0][2] if aspec else 0,
                    f"{arena_var} columns disagree with {wire_var} "
                    f"({detail}) — diffing and pointer casts follow this "
                    "order",
                ))
            for (wn, wd, wl), (an, ad, al) in zip(wspec, aspec):
                if wn == an and _canon(wd) != _canon(ad):
                    out.append(Finding(
                        self.name, self.arena, al,
                        f"column {an!r}: arena dtype {ad} vs wire dtype "
                        f"{wd} — the engine reads raw pointers at the "
                        "declared width",
                    ))
            if enc_tree is not None:
                fields = _dataclass_fields(enc_tree, enc_cls)
                if fields is not None and set(fields) != set(wnames):
                    missing = sorted(set(fields) - set(wnames))
                    stray = sorted(set(wnames) - set(fields))
                    out.append(Finding(
                        self.name, self.wire, wspec[0][2] if wspec else 0,
                        f"{wire_var} does not cover {enc_cls} exactly "
                        f"(missing={missing} stray={stray}) — un-listed "
                        "columns vanish at the seam",
                    ))
        out += self._check_trace(wire_tree)
        out += self._check_cand_state(arena_tree)
        return out

    def _check_cand_state(self, arena_tree: ast.AST) -> list[Finding]:
        """Fourth dtype site: the arena's persistent candidate structure
        (forward lists + reverse keys + slack shadow). These arrays ride
        checkpoint journal frames and live-migration handoffs, so their
        widths are as durable as the trace tables: _CAND_STATE_DTYPES
        must exist and cover exactly the cand_* keys export_state emits
        (restore_state coerces through the same table)."""
        export_fn = None
        for node in ast.walk(arena_tree):
            if isinstance(node, ast.FunctionDef) and node.name == "export_state":
                export_fn = node
                break
        if export_fn is None:
            return []  # fixture subsets without the arena class: no seam
        spec = _dict_spec(arena_tree, "_CAND_STATE_DTYPES")
        if spec is None:
            return [Finding(
                self.name, self.arena, export_fn.lineno,
                "missing dtype table _CAND_STATE_DTYPES — the persisted "
                "candidate structure's widths are an on-disk contract",
            )]
        declared = {n for n, _, _ in spec}
        emitted = set()
        for node in ast.walk(export_fn):
            if isinstance(node, ast.Dict):
                for key in node.keys:
                    if (
                        isinstance(key, ast.Constant)
                        and isinstance(key.value, str)
                        and key.value.startswith("cand")
                    ):
                        emitted.add(key.value)
        out: list[Finding] = []
        if declared != emitted:
            missing = sorted(emitted - declared)
            stray = sorted(declared - emitted)
            out.append(Finding(
                self.name, self.arena, spec[0][2] if spec else 0,
                f"_CAND_STATE_DTYPES does not cover export_state's cand_* "
                f"keys exactly (missing={missing} stray={stray}) — an "
                "undeclared persisted array restores at a guessed width",
            ))
        return out

    def _check_trace(self, wire_tree: ast.AST) -> list[Finding]:
        """Third dtype site: the flight-recorder frame codec. Trace files
        persist across code revisions, so its tables must mirror the wire
        tables EXACTLY (names, order, dtype) — drift silently reinterprets
        every archived trace's raw bytes at the wrong width on replay."""
        if not self.trace:
            return []
        out: list[Finding] = []
        trace_tree = self._parse(self.trace)
        if trace_tree is None:
            return [Finding(
                self.name, self.trace, 0,
                "cannot locate the trace dtype tables to cross-check",
            )]
        for wire_var, trace_var in (
            ("P_WIRE_DTYPES", "P_TRACE_DTYPES"),
            ("R_WIRE_DTYPES", "R_TRACE_DTYPES"),
        ):
            wspec = _dict_spec(wire_tree, wire_var)
            if wspec is None:
                continue  # already reported by the wire/arena pass
            tspec = _dict_spec(trace_tree, trace_var)
            if tspec is None:
                out.append(Finding(
                    self.name, self.trace, 0,
                    f"missing dtype table {trace_var}",
                ))
                continue
            wnames = [n for n, _, _ in wspec]
            tnames = [n for n, _, _ in tspec]
            if wnames != tnames:
                extra_w = [n for n in wnames if n not in tnames]
                extra_t = [n for n in tnames if n not in wnames]
                detail = (
                    f"wire-only={extra_w} trace-only={extra_t}"
                    if (extra_w or extra_t)
                    else "same columns, different order"
                )
                out.append(Finding(
                    self.name, self.trace,
                    tspec[0][2] if tspec else 0,
                    f"{trace_var} columns disagree with {wire_var} "
                    f"({detail}) — archived trace frames decode by this "
                    "table",
                ))
            for (wn, wd, _wl), (tn, td, tl) in zip(wspec, tspec):
                if wn == tn and _canon(wd) != _canon(td):
                    out.append(Finding(
                        self.name, self.trace, tl,
                        f"column {tn!r}: trace dtype {td} vs wire dtype "
                        f"{wd} — archived traces would reinterpret raw "
                        "bytes at the wrong width on replay",
                    ))
        return out
