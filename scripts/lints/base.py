"""Rule framework for the project lint engine (``python -m scripts.lints``).

The reference repo enforces correctness statically — clippy ``-D
warnings`` fails its build. This port's equivalents are *project*
contracts no off-the-shelf linter knows about: bit-identical solver
results (no ambient nondeterminism in kernel paths), lock-held access to
shared session/arena state, canonical wire dtypes, and no dense O(P*T)
allocations outside the blocked kernels. Each rule here is one AST
visitor with a fixture-driven test (tests/test_lints.py): the fixture
seeds violations the rule must catch 100% of, and the real tree must
come back clean — so a refactor that breaks a contract fails CI the same
push, not three perf PRs later.

Writing a rule:

    @register
    class MyRule(Rule):
        name = "my-rule"
        suppress_token = "my-rule-ok"       # escape: `# lint: my-rule-ok`
        def applies(self, rel): ...          # repo-relative path filter
        def check(self, src): ...            # per-file AST pass
        def check_repo(self): ...            # optional cross-file pass

Suppression: a finding on a line containing ``# lint: <token>`` (or the
blanket ``# lint: ok``) is dropped — the annotation is the audit trail
for every deliberate exemption, like clippy's ``#[allow(...)]``.
"""

from __future__ import annotations

import ast
import dataclasses
import pathlib
import re
from typing import Optional

REPO = pathlib.Path(__file__).resolve().parents[2]

# default tree the engine walks (fixtures hold deliberate violations and
# are only ever linted explicitly, by the tests)
DEFAULT_ROOTS = ("protocol_tpu",)
SKIP_PARTS = {"__pycache__", "fixtures"}

# escape tokens owned by the whole-program analyzer
# (``python -m scripts.analysis``), which audits its own staleness
# WITHIN its scan scope — the lint-engine audit must neither flag them
# stale nor call them unknown there. OUTSIDE the owning analyzer's
# scope nobody would ever audit them, so the lint engine reports those
# as stale itself (an escape no pass can consume suppresses nothing by
# construction). Token -> owning pass's path scope ((), meaning "the
# whole lint walk", for the lock pass which scans all of protocol_tpu).
# Kept in sync with the analyzers' roots by tests/test_analysis.py.
EXTERNAL_SUPPRESS_SCOPES = {
    "lock-order-ok": (),
    "protocol-ok": ("protocol_tpu/services/scheduler_grpc.py",),
    "purity-ok": (
        "protocol_tpu/ops", "protocol_tpu/parallel",
        "protocol_tpu/sched/tpu_backend.py",
    ),
    "retrace-ok": (
        "protocol_tpu/ops", "protocol_tpu/parallel",
        "protocol_tpu/sched/tpu_backend.py",
    ),
    "spmd-ok": (
        "protocol_tpu/ops", "protocol_tpu/parallel",
        "protocol_tpu/sched/tpu_backend.py",
    ),
}
EXTERNAL_SUPPRESS_TOKENS = tuple(EXTERNAL_SUPPRESS_SCOPES)

_ESCAPE_RE = re.compile(r"#\s*lint:\s*([A-Za-z0-9_-]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class Source:
    """One parsed file handed to rules: text, line list, AST (with parent
    back-links so visitors can ask about enclosing scopes)."""

    def __init__(self, path: pathlib.Path):
        self.path = path
        try:
            self.rel = str(path.resolve().relative_to(REPO))
        except ValueError:
            self.rel = str(path)
        self.text = path.read_text()
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=str(path))
        # line numbers where an escape annotation actually suppressed a
        # finding this run — the stale-escape audit's evidence trail
        self.consumed_escapes: set[int] = set()
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                child._lint_parent = node  # type: ignore[attr-defined]

    def ancestors(self, node: ast.AST):
        cur = getattr(node, "_lint_parent", None)
        while cur is not None:
            yield cur
            cur = getattr(cur, "_lint_parent", None)

    def enclosing_function(self, node: ast.AST) -> Optional[str]:
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc.name
        return None

    def suppressed(self, line: int, token: str) -> bool:
        if 1 <= line <= len(self.lines):
            text = self.lines[line - 1]
            if f"lint: {token}" in text or "lint: ok" in text:
                self.consumed_escapes.add(line)
                return True
        return False


class Rule:
    name: str = ""
    suppress_token: str = ""

    def applies(self, rel: str) -> bool:
        raise NotImplementedError

    def check(self, src: Source) -> list[Finding]:
        return []

    def check_repo(self) -> list[Finding]:
        """Cross-file invariants (dtype contracts span three modules);
        run once per engine invocation, not per file."""
        return []

    def finding(self, src: Source, node, message: str) -> list[Finding]:
        line = getattr(node, "lineno", 0)
        if self.suppress_token and src.suppressed(line, self.suppress_token):
            return []
        return [Finding(self.name, src.rel, line, message)]


RULES: list[Rule] = []


def register(cls):
    RULES.append(cls())
    return cls


def iter_files(roots=DEFAULT_ROOTS) -> list[pathlib.Path]:
    out = []
    for root in roots:
        p = REPO / root if not pathlib.Path(root).is_absolute() else pathlib.Path(root)
        if p.is_file():
            out.append(p)
            continue
        for f in sorted(p.rglob("*.py")):
            if not SKIP_PARTS.intersection(f.parts):
                out.append(f)
    return out


def run_rules(
    roots=DEFAULT_ROOTS,
    rules: Optional[list[Rule]] = None,
    audit_escapes: bool = True,
) -> list[Finding]:
    """The engine: parse each file once, dispatch to every applicable
    rule, run the cross-file passes, then audit escape annotations.
    Returns all findings (empty == the build may proceed)."""
    active = RULES if rules is None else rules
    findings: list[Finding] = []
    audited: list[tuple] = []  # (rel, lines, consumed line set)
    for path in iter_files(roots):
        resolved = path.resolve()
        rel = (
            str(resolved.relative_to(REPO))
            if resolved.is_relative_to(REPO) else str(path)
        )
        # an explicitly-named file is linted by every rule — "lint this
        # file" beats path scoping (fixture tests and spot checks)
        explicit = str(path) in map(str, roots) or rel in roots
        applicable = [r for r in active if explicit or r.applies(rel)]
        if not applicable:
            # still audited: an escape annotation in a file no rule
            # even scans suppresses nothing by construction
            try:
                audited.append(
                    (rel, path.read_text().splitlines(), set())
                )
            except OSError:
                pass
            continue
        try:
            src = Source(path)
        except SyntaxError as e:
            findings.append(Finding(
                "syntax", rel, e.lineno or 0, f"syntax error: {e.msg}"
            ))
            continue
        for rule in applicable:
            findings.extend(rule.check(src))
        audited.append((rel, src.lines, src.consumed_escapes))
    for rule in active:
        findings.extend(rule.check_repo())
    if audit_escapes and rules is None:
        # only when the FULL catalog ran: a --rule subset run has not
        # given every escape its chance to suppress
        for rel, lines, consumed in audited:
            findings.extend(stale_escapes(rel, lines, consumed))
    return findings


def stale_escapes(rel: str, lines, consumed: set) -> list[Finding]:
    """The anti-rot audit: every ``# lint: <token>`` annotation must
    have suppressed a finding THIS run. A suppression that no longer
    suppresses anything is dead weight that silently licenses future
    violations on its line — reported (and failing the build) so
    escapes get removed the same push that obsoletes them."""
    own_tokens = {r.suppress_token for r in RULES if r.suppress_token}
    out: list[Finding] = []
    for lineno, text in enumerate(lines, 1):
        m = _ESCAPE_RE.search(text)
        if m is None:
            continue
        token = m.group(1)
        if token in EXTERNAL_SUPPRESS_SCOPES:
            scope = EXTERNAL_SUPPRESS_SCOPES[token]
            in_scope = not scope or any(
                rel == s or rel.startswith(s + "/") for s in scope
            )
            if in_scope:
                continue  # the owning analyzer audits it there
            out.append(Finding(
                "stale-escape", rel, lineno,
                f"escape '# lint: {token}' is outside the owning "
                "analyzer's scan scope — no pass can ever consume it",
            ))
            continue
        if token != "ok" and token not in own_tokens:
            out.append(Finding(
                "stale-escape", rel, lineno,
                f"unknown escape token {token!r} — not a rule escape "
                "in this engine or the analyzer",
            ))
            continue
        if lineno not in consumed:
            out.append(Finding(
                "stale-escape", rel, lineno,
                f"escape '# lint: {token}' suppresses no finding — "
                "remove it (suppressions must not rot)",
            ))
    return out
