"""ISA-dispatch rule: intrinsics live ONLY in the per-ISA section.

The native engine's determinism contract (ISSUE 16) hangs on a single
choke point: every vector instruction is confined to the delimited
``PER-ISA KERNELS`` section of ``native/assign_engine.cpp`` and reached
exclusively through the ``kIsaOps`` dispatch table, with the scalar row
as referee. A raw ``_mm256_*`` call sprinkled into an entry point
outside that section would (a) execute unconditionally — SIGILL on any
pre-AVX2 host the baseline ``-march=x86-64-v2`` build is supposed to
carry, because only section functions wear the ``target`` attributes —
and (b) fork the float pipeline outside the per-ISA golden contract, so
plans drift between hosts with no ISA tag naming why.

This rule makes the boundary mechanical, textually (the engine source
is C++; no AST here):

  * any intrinsic token — ``_mm*_...`` calls, ``__m128/__m256/__m512``
    vector types, ``__builtin_ia32_*`` — outside the
    ``==== BEGIN PER-ISA KERNELS (isa-dispatch)`` /
    ``==== END PER-ISA KERNELS (isa-dispatch)`` delimiters is a finding
    (one per line; target-attributed forward DECLARATIONS carry no
    intrinsic tokens and stay legal, so headers can pre-declare the
    section's kernels),
  * ``#include <immintrin.h>`` outside the section must carry the
    audited escape ``// lint: isa-dispatch-include``,
  * an unbalanced BEGIN/END pair is itself a finding — a truncated
    section would silently legalize everything below it.

Escape: ``// lint: isa-dispatch-ok`` on the offending line.
"""

from __future__ import annotations

import pathlib
import re

from scripts.lints.base import REPO, Finding, Rule, register

_BEGIN = "BEGIN PER-ISA KERNELS (isa-dispatch)"
_END = "END PER-ISA KERNELS (isa-dispatch)"

_INTRINSIC = re.compile(
    r"(_mm\d{0,3}_\w+|__m(?:128|256|512)[id]?\b|__builtin_ia32_\w+)"
)
_INCLUDE = re.compile(r"#\s*include\s*<x?immintrin\.h>")


@register
class IsaDispatchRule(Rule):
    name = "isa-dispatch"
    suppress_token = "isa-dispatch-ok"

    def __init__(self, native_glob: str = "native/*.cpp"):
        self.native_glob = native_glob

    def applies(self, rel: str) -> bool:
        # C++-only rule: the python walk never feeds it; everything
        # happens in the cross-file pass below
        return False

    def _files(self) -> list[pathlib.Path]:
        pattern = pathlib.Path(self.native_glob)
        if pattern.is_absolute():
            return sorted(pattern.parent.glob(pattern.name))
        return sorted(REPO.glob(self.native_glob))

    def check_repo(self) -> list[Finding]:
        out: list[Finding] = []
        for path in self._files():
            out.extend(self._check_file(path))
        return out

    def _check_file(self, path: pathlib.Path) -> list[Finding]:
        try:
            rel = str(path.resolve().relative_to(REPO))
        except ValueError:
            rel = str(path)
        lines = path.read_text(errors="replace").splitlines()
        out: list[Finding] = []
        inside = False
        begin_line = 0
        for lineno, text in enumerate(lines, 1):
            if _BEGIN in text:
                if inside:
                    out.append(Finding(
                        self.name, rel, lineno,
                        "nested PER-ISA section BEGIN (previous BEGIN at "
                        f"line {begin_line} never closed)",
                    ))
                inside, begin_line = True, lineno
                continue
            if _END in text:
                if not inside:
                    out.append(Finding(
                        self.name, rel, lineno,
                        "PER-ISA section END without a matching BEGIN",
                    ))
                inside = False
                continue
            if inside:
                continue
            if f"lint: {self.suppress_token}" in text or "lint: ok" in text:
                continue
            if _INCLUDE.search(text):
                if "lint: isa-dispatch-include" in text:
                    continue
                out.append(Finding(
                    self.name, rel, lineno,
                    "immintrin.h include without the audited "
                    "'// lint: isa-dispatch-include' escape — the header "
                    "is legal only as the section's token source",
                ))
                continue
            m = _INTRINSIC.search(text)
            if m is not None:
                out.append(Finding(
                    self.name, rel, lineno,
                    f"raw intrinsic {m.group(1)!r} outside the PER-ISA "
                    "KERNELS section — vector code must live in the "
                    "delimited section and route through the kIsaOps "
                    "dispatch table (baseline builds SIGILL otherwise)",
                ))
        if inside:
            out.append(Finding(
                self.name, rel, begin_line,
                "PER-ISA section BEGIN never closed — everything below "
                "it is silently exempt from the dispatch boundary",
            ))
        return out
