#!/usr/bin/env python
"""Full-stack soak: the six-pod topology + GCS-fake + Loki-fake +
IPFS-fake wired SIMULTANEOUSLY, driven for >= --duration seconds with
node churn and a mid-run orchestrator restart, asserting the warm-path
matcher stats through real heartbeats (VERDICT r3 item 6; exceeds the
reference's manual `make up` walkthrough, reference Makefile:76-116 —
scripted, with artifacts).

Topology (one OS process per service, the Helm shape):
  ledger-api, kv-api, scheduler gRPC, discovery, orchestrator
  (kv-backed store so a restart keeps state), N workers
  (subprocess runtime, IPFS mirror + Loki shipping enabled).
In-process fakes: signature-verifying GCS bucket (tests/fake_bucket),
kubo /api/v0/add, Loki /loki/api/v1/push.

Timeline (fractions of --duration):
  t=0       bounded anchor task (replicas, long-lived) + artifact tasks
  35%       kill one worker (churn out)
  45%       start a replacement worker with a fresh node key (churn in)
  60%       SIGTERM + respawn the orchestrator (state must survive)
  steady    an artifact task every ~30 s; /scheduler/stats sampled ~5 s

Pass criteria (all asserted, artifact JSON written to --artifact):
  - warm solves observed (last_solve_stats.warm true at least once)
  - churn visible to the warm path (cache_delta_rows > 0 after churn-in)
  - artifact tasks created AFTER the orchestrator restart complete
  - the GCS fake holds verified uploads; kubo mirrored; Loki got pushes
  - the replacement node turns HEALTHY; the killed one leaves HEALTHY

Usage: python scripts/soak_full_stack.py [--duration 600] [--workers 6]
       (--duration 90 is the smoke setting; 600 is the soak bar)
"""

from __future__ import annotations

import argparse
import asyncio
import base64
import http.server
import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


# ---------------------------------------------------------------- fakes

def start_fake_loki():
    pushes = []

    class H(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
            try:
                pushes.append(json.loads(body))
            except ValueError:
                pushes.append({"raw": True})
            self.send_response(204)
            self.end_headers()

        def log_message(self, *a):
            pass

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, f"http://127.0.0.1:{srv.server_port}", pushes


def start_aiohttp_fakes():
    """FakeBucket (GCS signature verification) + fake kubo in one thread."""
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from aiohttp import web

    from tests.fake_bucket import FakeBucket

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    pem = key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption(),
    ).decode()
    creds = base64.b64encode(json.dumps({
        "client_email": "soak@fake.iam.gserviceaccount.com",
        "private_key": pem,
    }).encode()).decode()
    bucket = FakeBucket(rsa_public_key=key.public_key())

    kubo_adds = []

    async def kubo_add(request):
        reader = await request.multipart()
        part = await reader.next()
        data = await part.read()
        kubo_adds.append({"name": part.filename, "bytes": len(data)})
        return web.json_response(
            {"Hash": f"Qm{len(kubo_adds):044d}", "Size": str(len(data))}
        )

    kubo = web.Application()
    kubo.router.add_post("/api/v0/add", kubo_add)

    ports = {}
    ready = threading.Event()

    def _run():
        async def main():
            for name, app in (("bucket", bucket.make_app()), ("kubo", kubo)):
                runner = web.AppRunner(app)
                await runner.setup()
                site = web.TCPSite(runner, "127.0.0.1", 0)
                await site.start()
                ports[name] = site._server.sockets[0].getsockname()[1]
            ready.set()
            while True:
                await asyncio.sleep(3600)

        asyncio.new_event_loop().run_until_complete(main())

    threading.Thread(target=_run, daemon=True).start()
    ready.wait(10)
    return creds, bucket, kubo_adds, ports


# ---------------------------------------------------------------- pods

def wait_http(url, timeout=60):
    t0 = time.time()
    while time.time() - t0 < timeout:
        try:
            urllib.request.urlopen(url, timeout=2)
            return True
        except Exception:
            time.sleep(0.5)
    return False


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


class Stack:
    def __init__(self, args, creds, loki_url, kubo_url):
        self.args = args
        self.procs: dict[str, subprocess.Popen] = {}
        self.logdir = tempfile.mkdtemp(prefix="soak_logs_")
        self.state = tempfile.mkdtemp(prefix="soak_state_")
        self.creds = creds
        self.loki_url = loki_url
        self.kubo_url = kubo_url
        from protocol_tpu.security import (
            EvmRecoveryWallet,
            EvmWallet,
            Wallet,
        )

        wcls = {
            "ed25519": Wallet,
            "evm": EvmWallet,
            "evm-recovery": EvmRecoveryWallet,
        }[args.wallet_scheme]
        self.wallets = {
            n: wcls.from_seed(f"soak-{n}".encode())
            for n in ("manager", "creator", "validator")
        }
        # one provider per worker: each registration stakes for one node,
        # and a shared provider runs out of staked balance at N nodes
        self.node_keys = [
            wcls.from_seed(f"soak-node-{i}".encode())
            for i in range(args.workers + 4)  # spares for churn-ins
        ]
        self.provider_keys = [
            wcls.from_seed(f"soak-provider-{i}".encode())
            for i in range(args.workers + 4)
        ]
        self.ports = {
            "ledger": free_port(), "kv": free_port(), "disc": free_port(),
            "orch": free_port(), "validator": free_port(),
            "sched": free_port(),
        }
        self.worker_ports = [free_port() for _ in self.node_keys]
        self.base_env = dict(
            os.environ,
            PROTOCOL_TPU_FORCE_PLATFORM="cpu",
            LEDGER_API_KEY="admin",
            KV_API_KEY="admin",
            # pods derive their identity from hex keys under the SAME
            # scheme the script-side wallets use, or addresses mismatch
            PROTOCOL_TPU_WALLET_SCHEME=args.wallet_scheme,
        )

    def url(self, name):
        return f"http://127.0.0.1:{self.ports[name]}"

    def spawn(self, name, cmd, env=None):
        log = open(os.path.join(self.logdir, f"{name}.log"), "ab")
        p = subprocess.Popen(
            cmd, env=env or self.base_env, stdout=log, stderr=log, cwd=REPO
        )
        self.procs[name] = p
        return p

    def serve(self, name, service, *flags, env=None):
        return self.spawn(
            name, [sys.executable, "-m", "protocol_tpu.serve", service, *flags],
            env=env,
        )

    def cli(self, *argv, orchestrator=False):
        target = (
            ["--orchestrator", self.url("orch")]
            if orchestrator else ["--ledger", self.url("ledger")]
        )
        out = subprocess.run(
            [sys.executable, "-m", "protocol_tpu.cli", *target,
             "--api-key", "admin", *argv],
            capture_output=True, text=True, env=self.base_env, cwd=REPO,
        )
        if out.returncode != 0:
            raise RuntimeError(f"cli {argv}: {out.stderr.strip()[-400:]}")
        return out.stdout

    def orchestrator_cmd_env(self):
        env = dict(
            self.base_env,
            MANAGER_KEY=self.wallets["manager"].private_key_hex(),
            ADMIN_API_KEY="admin",
            DISCOVERY_URLS=self.url("disc"),
            HEARTBEAT_URL=self.url("orch"),
            S3_CREDENTIALS=self.creds,
            BUCKET_NAME="soak-bucket",
            STORAGE_ENDPOINT=f"http://127.0.0.1:{self.bucket_port}",
            LOKI_URL=self.loki_url,
            # force the production sparse + candidate-cache + warm path
            # at soak fleet size (dense cutover would hide warm stats)
            PROTOCOL_TPU_DENSE_CELL_BUDGET="1",
            # the reference-parity default (3/address/hour) exhausts in
            # minutes at soak cadence and would mask real upload breakage
            UPLOADS_PER_HOUR="1000",
        )
        flags = [
            "--ledger-url", self.url("ledger"), "--pool-id", "0",
            "--port", str(self.ports["orch"]), "--kv-url", self.url("kv"),
        ]
        return flags, env

    def start_orchestrator(self):
        flags, env = self.orchestrator_cmd_env()
        self.serve("orch", "orchestrator", *flags, env=env)

    def start_worker(self, idx):
        w = self.node_keys[idx]
        env = dict(
            self.base_env,
            PROVIDER_KEY=self.provider_keys[idx].private_key_hex(),
            NODE_KEY=w.private_key_hex(),
            IPFS_API_URL=self.kubo_url,
            LOKI_URL=self.loki_url,
        )
        self.serve(
            f"worker{idx}", "worker",
            "--ledger-url", self.url("ledger"), "--pool-id", "0",
            "--port", str(self.worker_ports[idx]),
            "--discovery-urls", self.url("disc"),
            "--runtime", "subprocess",
            "--socket-path", f"/tmp/soak-{os.getpid()}-{idx}.sock",
            env=env,
        )
        return w.address

    def up(self, bucket_port):
        self.bucket_port = bucket_port
        self.serve("ledger", "ledger-api", "--port", str(self.ports["ledger"]),
                   "--state-dir", self.state)
        assert wait_http(self.url("ledger") + "/health"), "ledger-api down"
        w = self.wallets
        for pk in self.provider_keys:
            self.cli("mint", "--address", pk.address, "--amount", "100000")
        self.cli("create-domain", "--name", "soak")
        self.cli("create-pool", "--domain-id", "0",
                 "--creator", w["creator"].address,
                 "--manager", w["manager"].address)
        self.cli("start-pool", "--pool-id", "0",
                 "--caller", w["creator"].address)
        req = urllib.request.Request(
            self.url("ledger") + "/ledger/write/grant_validator_role",
            data=json.dumps({"address": w["validator"].address}).encode(),
            headers={"Authorization": "Bearer admin",
                     "Content-Type": "application/json"},
        )
        urllib.request.urlopen(req, timeout=5)

        self.serve("kv", "kv-api", "--port", str(self.ports["kv"]),
                   "--state-dir", self.state,
                   env=dict(self.base_env, KV_API_KEY="admin"))
        self.serve("sched", "scheduler",
                   "--address", f"127.0.0.1:{self.ports['sched']}")
        self.serve("disc", "discovery",
                   "--ledger-url", self.url("ledger"), "--pool-id", "0",
                   "--port", str(self.ports["disc"]),
                   # every worker shares 127.0.0.1 here; the default
                   # per-IP cap (5 pool-active nodes) silently rejected
                   # the churn-in replacement in the first 600 s run
                   "--max-nodes-per-ip", "64",
                   env=dict(self.base_env, ADMIN_API_KEY="admin"))
        assert wait_http(self.url("kv") + "/health"), "kv-api down"
        assert wait_http(self.url("disc") + "/health"), "discovery down"
        self.start_orchestrator()
        assert wait_http(self.url("orch") + "/health"), "orchestrator down"
        self.serve("validator", "validator",
                   "--ledger-url", self.url("ledger"), "--pool-id", "0",
                   "--port", str(self.ports["validator"]),
                   env=dict(self.base_env,
                            VALIDATOR_KEY=w["validator"].private_key_hex(),
                            DISCOVERY_URLS=self.url("disc")))
        for i in range(self.args.workers):
            self.start_worker(i)
        # whitelist AFTER self-registration or the monitor ejects the nodes
        deadline = time.time() + 90
        pending = {pk.address for pk in self.provider_keys[: self.args.workers]}
        while pending and time.time() < deadline:
            for addr in list(pending):
                try:
                    self.cli("whitelist-provider", "--provider", addr)
                    pending.discard(addr)
                except RuntimeError:
                    pass
            time.sleep(2)

    def whitelist(self, idx):
        deadline = time.time() + 60
        while time.time() < deadline:
            try:
                self.cli("whitelist-provider",
                         "--provider", self.provider_keys[idx].address)
                return
            except RuntimeError:
                time.sleep(2)

    def admin_get(self, path):
        req = urllib.request.Request(
            self.url("orch") + path,
            headers={"Authorization": "Bearer admin"},
        )
        with urllib.request.urlopen(req, timeout=5) as r:
            return json.loads(r.read())["data"]

    def stop(self, name, sig=signal.SIGTERM, wait=15):
        p = self.procs.pop(name, None)
        if p is None:
            return
        p.send_signal(sig)
        try:
            p.wait(wait)
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait(5)

    def teardown(self):
        for name in list(self.procs):
            self.stop(name, wait=5)
        shutil.rmtree(self.state, ignore_errors=True)


# comma-separated argv for the CLI's --cmd; the payload lives in a file
# because the separator rules out inline `python -c` code
ARTIFACT_TASK_CMD = ",".join(
    [sys.executable, "-S", os.path.join(REPO, "scripts", "soak_task.py")]
)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--duration", type=float, default=600.0)
    ap.add_argument("--workers", type=int, default=6)
    ap.add_argument("--artifact", default="artifacts/soak_run.json")
    ap.add_argument(
        "--wallet-scheme", default="ed25519",
        choices=["ed25519", "evm", "evm-recovery"],
        help="signature scheme for EVERY identity in the stack "
             "(evm-recovery = the reference's literal r||s||v wire)",
    )
    args = ap.parse_args()

    loki_srv, loki_url, loki_pushes = start_fake_loki()
    creds, bucket, kubo_adds, fports = start_aiohttp_fakes()
    kubo_url = f"http://127.0.0.1:{fports['kubo']}"

    stack = Stack(args, creds, loki_url, kubo_url)
    events, samples = [], []

    def ev(kind, **kw):
        events.append({"t": round(time.time() - t0, 1), "kind": kind, **kw})
        print(f"[{events[-1]['t']:7.1f}s] {kind} {kw}", flush=True)

    t0 = time.time()
    ok = False
    try:
        stack.up(fports["bucket"])
        ev("stack_up", workers=args.workers)

        # long-lived bounded anchor: stable warm seeds across solves
        # replicas=2 of --workers nodes: bounded tasks win phase 1,
        # so the anchor must leave spare nodes for the artifact tasks
        stack.cli("create-task", "--name", "anchor", "--image", "py",
                  "--cmd", "sleep,99999", "--replicas", "2",
                  orchestrator=True)
        # ladder #5 under soak: colocated replicas stack onto one
        # provider while RAM capacity holds (the hosts have no GPUs, so
        # the demand vector is memory-shaped), each replica running
        # CONCURRENTLY in its own worker runtime
        import urllib.request as _rq
        req = _rq.Request(
            stack.url("orch") + "/tasks",
            data=json.dumps({
                "name": "colo", "image": "py",
                "cmd": ["sleep", "99999"],
                "scheduling_config": {"plugins": {"tpu_scheduler": {
                    "replicas": ["4"], "colocate": ["true"],
                    "compute_requirements": ["ram_mb=64"],
                }}},
            }).encode(),
            headers={"Authorization": "Bearer admin",
                     "Content-Type": "application/json"},
        )
        _rq.urlopen(req, timeout=10)
        art_n = 0

        def art_task():
            nonlocal art_n
            art_n += 1
            stack.cli(
                "create-task", "--name", f"art{art_n}", "--image", "py",
                "--cmd", ARTIFACT_TASK_CMD, orchestrator=True,
            )
            return f"art{art_n}"

        D = args.duration
        objects_at_restart = 0
        churn_out_at, churn_in_at, restart_at = 0.35 * D, 0.45 * D, 0.60 * D
        done_marks = {"churn_out": False, "churn_in": False, "restart": False}
        post_restart_tasks: list[str] = []
        next_art = 20.0
        replacement_addr = None
        killed_addr = stack.node_keys[0].address

        while time.time() - t0 < D:
            now = time.time() - t0
            if now >= churn_out_at and not done_marks["churn_out"]:
                stack.stop("worker0")
                done_marks["churn_out"] = True
                ev("churn_out", addr=killed_addr)
            if now >= churn_in_at and not done_marks["churn_in"]:
                replacement_addr = stack.start_worker(args.workers)
                stack.whitelist(args.workers)
                done_marks["churn_in"] = True
                ev("churn_in", addr=replacement_addr)
            if (
                done_marks["churn_in"]
                and not done_marks.get("churn_in_seen")
            ):
                try:
                    known = {
                        n["address"] for n in stack.admin_get("/nodes")
                    }
                    if replacement_addr in known:
                        done_marks["churn_in_seen"] = True
                        ev("churn_in_registered")
                except Exception:
                    pass
            if now >= restart_at and not done_marks["restart"]:
                objects_at_restart = len(bucket.objects)
                stack.stop("orch")
                stack.start_orchestrator()
                assert wait_http(stack.url("orch") + "/health", 60), (
                    "orchestrator did not come back"
                )
                done_marks["restart"] = True
                ev("orchestrator_restarted")
            if now >= next_art:
                name = art_task()
                if done_marks["restart"]:
                    post_restart_tasks.append(name)
                ev("task_created", name=name)
                next_art += 30.0
            try:
                stats = stack.admin_get("/scheduler/stats")
                stats["_t"] = round(now, 1)
                stats["_post_churn_in"] = done_marks["churn_in"]
                samples.append(stats)
            except Exception as e:
                ev("stats_error", error=str(e)[:120])
            time.sleep(5)

        # ---- final state reads
        nodes = stack.admin_get("/nodes")
        tasks = stack.admin_get("/tasks")
        by_name = {t["name"]: t for t in tasks}
        node_status = {n["address"]: n.get("status") for n in nodes}

        # allow in-flight post-restart uploads a grace window: NEW
        # verified bucket objects after the restart prove tasks created
        # post-restart ran end to end (task state lives per NODE in this
        # design — reference heartbeat.rs parity — so the Task object
        # itself has no COMPLETED transition to poll)
        grace = time.time() + 90
        while time.time() < grace and len(bucket.objects) <= objects_at_restart:
            time.sleep(5)

        # ---- assertions
        problems = []
        if not any(s.get("warm") for s in samples):
            problems.append("no warm solve observed")
        if not any(s.get("colocated_slots", 0) >= 2 for s in samples):
            problems.append(
                "colocation never stacked >=2 replicas (ladder #5 silent)"
            )
        if not any(
            s.get("_post_churn_in") and s.get("cache_delta_rows", 0) > 0
            for s in samples
        ):
            problems.append("churn never reached the warm path "
                            "(cache_delta_rows stayed 0 after churn-in)")
        if not post_restart_tasks:
            problems.append("no tasks were created after the restart")
        elif len(bucket.objects) <= objects_at_restart:
            problems.append(
                "no new verified uploads after the orchestrator restart "
                f"({len(bucket.objects)} total, {objects_at_restart} before)"
            )
        anchored = [
            a for a, n in (
                (nn["address"], nn) for nn in nodes
            ) if n.get("task_state") == "RUNNING"
        ]
        if not anchored:
            problems.append("no node reports a RUNNING task (anchor lost)")
        if not bucket.objects:
            problems.append("fake bucket holds no verified artifacts")
        if bucket.rejections:
            problems.append(f"bucket rejected uploads: {bucket.rejections[:3]}")
        if not kubo_adds:
            problems.append("kubo mirror saw no adds")
        if not loki_pushes:
            problems.append("loki saw no pushes")
        healthy = {"healthy"}
        if replacement_addr and str(
            node_status.get(replacement_addr)
        ).lower() not in healthy:
            problems.append(
                f"replacement node status={node_status.get(replacement_addr)}"
            )
        if str(node_status.get(killed_addr)).lower() in healthy:
            problems.append("killed node still Healthy at soak end")

        ok = not problems
        report = {
            "ok": ok,
            "duration_s": round(time.time() - t0, 1),
            "workers": args.workers,
            "wallet_scheme": args.wallet_scheme,
            "problems": problems,
            "events": events,
            "warm_solves": sum(1 for s in samples if s.get("warm")),
            "max_colocated_slots": max(
                (s.get("colocated_slots", 0) for s in samples), default=0
            ),
            "samples_total": len(samples),
            "bucket_objects": len(bucket.objects),
            "kubo_adds": len(kubo_adds),
            "loki_pushes": len(loki_pushes),
            "node_status": node_status,
            "node_tasks": {
                n["address"]: [n.get("task_id"), n.get("task_state")]
                for n in nodes
            },
            "sample_tail": samples[-5:],
        }
        os.makedirs(os.path.dirname(args.artifact) or ".", exist_ok=True)
        with open(args.artifact, "w") as f:
            json.dump(report, f, indent=1)
        print(json.dumps({k: report[k] for k in
                          ("ok", "problems", "warm_solves", "bucket_objects",
                           "kubo_adds", "loki_pushes")}, indent=1))
        return 0 if ok else 1
    finally:
        stack.teardown()
        loki_srv.shutdown()


if __name__ == "__main__":
    sys.exit(main())
