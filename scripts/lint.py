#!/usr/bin/env python
"""Hermetic lint gate: syntax + unused-import check over the package and
tests, runnable with no third-party linter installed (the CI `checks.yml`
lint job additionally runs ruff with the matching rule set — E9,F63,F7,
F82,F401 — the fail-the-build discipline of the reference's clippy
`-D warnings`, .github/workflows/checks.yml:35-41 there)."""

from __future__ import annotations

import ast
import pathlib
import sys


def unused_imports(path: pathlib.Path) -> list[str]:
    src = path.read_text()
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [f"{path}:{e.lineno}: syntax error: {e.msg}"]
    imported: dict[str, int] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "__future__":
            continue
        if isinstance(node, ast.Import):
            for a in node.names:
                imported[(a.asname or a.name).split(".")[0]] = node.lineno
        elif isinstance(node, ast.ImportFrom):
            for a in node.names:
                if a.name != "*":
                    imported[a.asname or a.name] = node.lineno
    used = {n.id for n in ast.walk(tree) if isinstance(n, ast.Name)}
    out = []
    for name, line in imported.items():
        # attribute roots and string references (docstring examples,
        # __all__, fixtures) count as uses — cheap textual fallback
        if name in used or f"{name}." in src or f'"{name}"' in src or f"'{name}'" in src:
            continue
        out.append(f"{path}:{line}: unused import {name}")
    return out


def main() -> int:
    roots = sys.argv[1:] or ["protocol_tpu", "tests", "scripts"]
    findings: list[str] = []
    for root in roots:
        p = pathlib.Path(root)
        files = [p] if p.is_file() else sorted(p.rglob("*.py"))
        for f in files:
            findings += unused_imports(f)
    print("\n".join(findings) or f"lint clean ({', '.join(roots)})")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
