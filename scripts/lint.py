#!/usr/bin/env python
"""Hermetic lint gate: syntax + unused-import check over the package and
tests, runnable with no third-party linter installed (the CI `checks.yml`
lint job additionally runs ruff with the matching rule set — E9,F63,F7,
F82,F401 — the fail-the-build discipline of the reference's clippy
`-D warnings`, .github/workflows/checks.yml:35-41 there)."""

from __future__ import annotations

import ast
import pathlib
import re
import sys


def _string_uses(tree: ast.Module) -> set[str]:
    """Names referenced as STRINGS in the only contexts where a string
    really does resolve an import at runtime: ``__all__`` export lists
    and pytest fixture lookups (``usefixtures``/``getfixturevalue``/
    fixture params). The old fallback counted ANY quoted occurrence
    anywhere in the source — one docstring or log message mentioning the
    name suppressed a real unused-import finding."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            if any(
                isinstance(t, ast.Name) and t.id == "__all__" for t in targets
            ) and isinstance(node.value, (ast.List, ast.Tuple, ast.Set)):
                out |= {
                    e.value for e in node.value.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                }
        elif isinstance(node, ast.Call):
            fn = node.func
            fname = fn.id if isinstance(fn, ast.Name) else (
                fn.attr if isinstance(fn, ast.Attribute) else ""
            )
            if "fixture" in fname:
                out |= {
                    a.value for a in node.args
                    if isinstance(a, ast.Constant) and isinstance(a.value, str)
                }
    return out


_IDENT = re.compile(r"[A-Za-z_][A-Za-z0-9_]*")


def _annotation_uses(tree: ast.Module) -> set[str]:
    """Identifiers inside STRING type annotations (forward references:
    ``Optional["TpuBatchMatcher"]`` with the import behind TYPE_CHECKING)
    — real uses the quoted-string fallback used to cover by accident."""
    out: set[str] = set()
    for node in ast.walk(tree):
        for ann in (
            getattr(node, "annotation", None), getattr(node, "returns", None)
        ):
            if ann is None:
                continue
            for sub in ast.walk(ann):
                if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                    out |= set(_IDENT.findall(sub.value))
    return out


def unused_imports(path: pathlib.Path) -> list[str]:
    src = path.read_text()
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [f"{path}:{e.lineno}: syntax error: {e.msg}"]
    imported: dict[str, int] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "__future__":
            continue
        if isinstance(node, ast.Import):
            for a in node.names:
                imported[(a.asname or a.name).split(".")[0]] = node.lineno
        elif isinstance(node, ast.ImportFrom):
            for a in node.names:
                if a.name != "*":
                    imported[a.asname or a.name] = node.lineno
    used = {n.id for n in ast.walk(tree) if isinstance(n, ast.Name)}
    used |= _string_uses(tree)
    used |= _annotation_uses(tree)
    out = []
    for name, line in imported.items():
        # attribute roots still count textually (cheap and low-risk);
        # string references only in __all__/fixture/annotation contexts
        if name in used or f"{name}." in src:
            continue
        out.append(f"{path}:{line}: unused import {name}")
    return out


def main() -> int:
    roots = sys.argv[1:] or ["protocol_tpu", "tests", "scripts"]
    findings: list[str] = []
    for root in roots:
        p = pathlib.Path(root)
        # scripts/lints/fixtures holds DELIBERATE violations (the lint
        # engine's seeded test corpus) — never lint it as product code
        files = [p] if p.is_file() else sorted(
            f for f in p.rglob("*.py") if "fixtures" not in f.parts
        )
        for f in files:
            findings += unused_imports(f)
    print("\n".join(findings) or f"lint clean ({', '.join(roots)})")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
