#!/usr/bin/env python
"""Sanitizer stress harness for the native engine (TSan / ASan+UBSan).

The multi-threaded engine's whole soundness story is an *unchecked*
invariant: bit-identical results for every thread count, from chunked
serial reductions and deterministic merges (native/assign_engine.cpp).
This harness makes that invariant machine-checked with the tool built for
the job — it builds the instrumented variant of the engine
(``libassign_engine.{tsan,asan}.so``), re-executes itself as a child
process under the matching LD_PRELOADed runtime with
``PROTOCOL_TPU_NATIVE_SANITIZE`` selecting the variant, drives all three
-mt kernels (``fused_topk_candidates_mt``, ``auction_sparse_mt``,
``sinkhorn_sparse_mt``) across thread counts {1, 2, 4, 8} through churned
warm re-solves (including the full ``NativeSolveArena`` dirty-row
pipeline), and FAILS on any sanitizer report (parsed from the
``log_path`` files TSAN_OPTIONS/ASAN_OPTIONS point at, plus the
``exitcode=66`` backstop).

The child deliberately imports only numpy + the native bindings — no jax:
the sanitizer runtimes slow everything ~10x and instrument nothing
outside the engine, so the control plane's import graph would be pure
drag (and its thread pools pure report noise).

Usage:
    python scripts/sanitize_native.py --sanitizer tsan
    python scripts/sanitize_native.py --sanitizer asan --ticks 5

The harness also runs the repo's .clang-tidy profile (bugprone-* /
concurrency-* / performance-*) over the engine source — the static half
of the same discipline. This pass is NON-OPTIONAL: a missing clang-tidy
binary FAILS the run (CI pins and installs it; a toolchain that
silently skips a static gate is a gate that rots). Containers without
the toolchain must say so explicitly with ``--skip-clang-tidy``.
"""

from __future__ import annotations

import argparse
import os
import shutil
import subprocess
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

# one report fingerprint per sanitizer family — any hit fails the run
_REPORT_MARKERS = (
    "WARNING: ThreadSanitizer",
    "ERROR: AddressSanitizer",
    "ERROR: LeakSanitizer",
    "runtime error:",  # UBSan
)
_SAN_EXITCODE = 66


# ---------------------------------------------------------------- child

def _synth_marketplace(rng, P: int, T: int):
    """Duck-typed EncodedProviders / EncodedRequirements namespaces built
    from plain numpy (no jax import; native.fused_topk_candidates only
    reads attributes). Same distributions as bench.py's
    synth_providers/synth_requirements — -1 sentinels for unconstrained
    requirement fields, radians for locations, production CostWeights
    scale — so the stress drives the engine through the bench's branch
    mix rather than an accidentally-adversarial population."""
    import numpy as np

    MODEL_CLASSES, K_OPT, W = 12, 2, 8

    def ns(**fields):
        o = type("_Enc", (), {})()
        for k, v in fields.items():
            setattr(o, k, v)
        return o

    providers = ns(
        gpu_count=rng.choice([1, 2, 4, 8], P).astype(np.int32),
        gpu_mem_mb=rng.choice([16000, 24000, 40000, 80000], P).astype(np.int32),
        gpu_model_id=rng.integers(0, MODEL_CLASSES, P).astype(np.int32),
        has_gpu=np.ones(P, bool),
        has_cpu=np.ones(P, bool),
        cpu_cores=rng.choice([8, 16, 32, 64], P).astype(np.int32),
        ram_mb=rng.choice([32768, 65536, 131072], P).astype(np.int32),
        storage_gb=rng.choice([500, 1000, 4000], P).astype(np.int32),
        lat=np.radians(rng.uniform(-60, 60, P)).astype(np.float32),
        lon=np.radians(rng.uniform(-180, 180, P)).astype(np.float32),
        has_location=np.ones(P, bool),
        price=rng.uniform(0.5, 4.0, P).astype(np.float32),
        load=rng.uniform(0, 1, P).astype(np.float32),
        valid=np.ones(P, bool),
    )
    mask = np.zeros((T, K_OPT, W), np.uint32)
    accept = rng.random((T, MODEL_CLASSES)) < 0.4
    accept[np.arange(T), rng.integers(0, MODEL_CLASSES, T)] = True
    for c in range(MODEL_CLASSES):
        mask[:, 0, c >> 5] |= np.where(
            accept[:, c], np.uint32(1) << np.uint32(c & 31), 0
        ).astype(np.uint32)
    opt_valid = np.zeros((T, K_OPT), bool)
    opt_valid[:, 0] = True
    count = np.full((T, K_OPT), -1, np.int32)
    count[:, 0] = rng.choice([-1, 1, 2, 4, 8], T, p=[0.4, 0.15, 0.15, 0.15, 0.15])
    mem_min = np.full((T, K_OPT), -1, np.int32)
    mem_min[:, 0] = rng.choice([-1, 16000, 40000], T, p=[0.5, 0.3, 0.2])
    requirements = ns(
        cpu_required=np.zeros(T, bool),
        cpu_cores=rng.choice([-1, 8, 16], T, p=[0.5, 0.3, 0.2]).astype(np.int32),
        ram_mb=rng.choice([-1, 32768], T, p=[0.6, 0.4]).astype(np.int32),
        storage_gb=rng.choice([-1, 500], T, p=[0.7, 0.3]).astype(np.int32),
        gpu_opt_valid=opt_valid,
        gpu_count=count,
        gpu_mem_min=mem_min,
        gpu_mem_max=np.full((T, K_OPT), -1, np.int32),
        gpu_total_mem_min=np.full((T, K_OPT), -1, np.int32),
        gpu_total_mem_max=np.full((T, K_OPT), -1, np.int32),
        gpu_model_mask=mask,
        gpu_model_constrained=opt_valid.copy(),
        lat=np.radians(rng.uniform(-60, 60, T)).astype(np.float32),
        lon=np.radians(rng.uniform(-180, 180, T)).astype(np.float32),
        has_location=np.ones(T, bool),
        priority=np.zeros(T, np.float32),
        valid=np.ones(T, bool),
    )
    weights = ns(price=1.0, load=1.0, proximity=0.001, priority=0.0)
    return providers, requirements, weights


def _churn(rng, providers, requirements, frac: float):
    """One churn tick, mirroring production churn classes: price/load
    drift on a slice of providers (the arena's base-only fast path), a
    few structural provider edits, and a few re-posted tasks."""
    import numpy as np

    P = providers.price.shape[0]
    T = requirements.cpu_cores.shape[0]
    drift = rng.choice(P, max(1, int(P * frac)), replace=False)
    price = providers.price.copy()
    load = providers.load.copy()
    price[drift] = np.maximum(0, price[drift] + rng.normal(0, 0.3, drift.size)).astype(np.float32)
    load[drift] = np.clip(load[drift] + rng.normal(0, 0.1, drift.size), 0, 1).astype(np.float32)
    providers.price, providers.load = price, load
    struct = rng.choice(P, max(1, int(P * frac / 4)), replace=False)
    cores = providers.cpu_cores.copy()
    cores[struct] = rng.choice([8, 16, 32, 64], struct.size)
    providers.cpu_cores = cores
    tasks = rng.choice(T, max(1, int(T * frac / 4)), replace=False)
    ram = requirements.ram_mb.copy()
    ram[tasks] = rng.choice([-1, 32768], tasks.size)
    requirements.ram_mb = ram
    return drift, struct, tasks


def _assert_identical(results: dict, what: str) -> None:
    import numpy as np

    threads = sorted(results)
    ref = results[threads[0]]
    for t in threads[1:]:
        for i, (a, b) in enumerate(zip(ref, results[t])):
            if not np.array_equal(a, b):
                raise SystemExit(
                    f"THREAD-INVARIANCE BROKEN: {what} output {i} differs "
                    f"between threads={threads[0]} and threads={t}"
                )
    print(f"  [child] {what}: bit-identical across threads={threads}")


def _child(args) -> int:
    import numpy as np

    from protocol_tpu import native

    variant = native.sanitize_variant()
    print(f"[child] engine variant={variant or 'plain'} "
          f"so={os.path.basename(native.so_path(variant))}")
    native.load()
    # the parent forces one runtime ISA per child (PROTOCOL_TPU_NATIVE_ISA)
    # so the vector dispatch paths — lane kernels, block-skip survivors,
    # tiled sweeps — run UNDER the sanitizer, not just the scalar referee.
    # A clamp here means the parent's support probe and the instrumented
    # build disagree about the host: fail loudly, don't stress the wrong
    # pipeline.
    requested = native.isa_request()
    effective = native.current_isa()
    print(f"[child] runtime isa={effective} (requested {requested or 'default'})")
    if requested not in (None, "auto") and effective != requested:
        raise SystemExit(
            f"ISA CLAMPED: requested {requested} but engine runs "
            f"{effective} — host/build support mismatch"
        )
    threads = [int(t) for t in args.threads.split(",")]
    P, T, K = args.providers, args.tasks, args.top_k

    # --- stress 1: fused cost+top-k (the task-chunked MT pass + the
    # deterministic reverse-edge merge), fresh inputs per thread count
    rng = np.random.default_rng(7)
    ep, er, w = _synth_marketplace(rng, P, T)
    fused = {}
    for t in threads:
        cp, cc = native.fused_topk_candidates(ep, er, w, k=K, threads=t)
        fused[t] = (cp.copy(), cc.copy())
    _assert_identical(fused, "fused_topk_candidates_mt")
    cand_p, cand_c = fused[threads[0]]

    # --- stress 1b: capability-bucket pruner + incremental repair chain
    # (the persistent-candidate warm path): bucketed cold must equal the
    # full scan bit-for-bit, and a churned repair chain must stay
    # thread-invariant AND bit-identical to from-scratch rebuilds — the
    # repair kernel's parallel phases (pooled column sweeps, merges,
    # reverse strip/fold, subset scatter) all run under the sanitizer
    for t in threads:
        got = native.fused_topk_candidates(
            ep, er, w, k=K, threads=t, bucketed=True
        )
        if not (np.array_equal(got[0], cand_p)
                and np.array_equal(got[1], cand_c)):
            raise SystemExit(
                "BUCKETED PRUNER NOT EXACT: bucketed cold generation "
                f"differs from the full scan at threads={t}"
            )
    repair_runs = {}
    for t in threads:
        crng = np.random.default_rng(29)
        ep_t, er_t, w_t = _synth_marketplace(np.random.default_rng(7), P, T)
        rev = np.zeros((P, 8), np.uint64)
        slack = (np.zeros((T, 8), np.int32), np.zeros((T, 8), np.float32))
        cp, cc = native.fused_topk_candidates(
            ep_t, er_t, w_t, k=K, threads=t, bucketed=True,
            rev_out=rev, slack_out=slack,
        )
        trace = [cp.copy(), cc.copy(), rev.copy()]
        for _ in range(max(2, args.ticks // 2)):
            drift, struct, tasks = _churn(crng, ep_t, er_t, frac=0.02)
            dirty_p = np.union1d(drift, struct).astype(np.int32)
            touched, changed = native.repair_topk_candidates(
                ep_t, er_t, w_t, cp, cc, rev,
                dirty_p, tasks.astype(np.int32),
                k=K, threads=t, slack=slack,
            )
            trace += [cp.copy(), cc.copy(), rev.copy(),
                      touched.copy(), changed.copy()]
        repair_runs[t] = trace
        if t == threads[0]:
            rev_ref = np.zeros((P, 8), np.uint64)
            rp, rc = native.fused_topk_candidates(
                ep_t, er_t, w_t, k=K, threads=t, rev_out=rev_ref
            )
            if not (np.array_equal(cp, rp) and np.array_equal(cc, rc)
                    and np.array_equal(rev, rev_ref)):
                raise SystemExit(
                    "REPAIR NOT EXACT: repaired candidate structure "
                    "differs from a from-scratch rebuild"
                )
    _assert_identical(repair_runs, "repair_topk_candidates_mt chain")

    # --- stress 2: warm auction chain (Jacobi bidding rounds, per-thread
    # bid buffers, eps-CS repair, seat eviction caps) with churned costs;
    # the outcome taxonomy + margins ride the same invariance check
    chains = {}
    for t in threads:
        crng = np.random.default_rng(11)
        cc_t = cand_c.copy()
        outs: dict = {}
        p4t, price, retired = native.auction_sparse_mt(
            cand_p, cc_t, num_providers=P, threads=t, outcomes=outs
        )
        trace = [p4t.copy(), price.copy(),
                 outs["codes"].copy(), outs["margin"].copy()]
        for _ in range(args.ticks):
            rows = crng.choice(T, max(1, T // 50), replace=False)
            cc_t[rows] *= (0.8 + 0.4 * crng.random((rows.size, 1))).astype(np.float32)
            retired = retired.copy()
            retired[rows] = False
            mask = np.zeros(T, bool)
            mask[rows] = True
            outs = {}
            p4t, price, retired = native.auction_sparse_mt(
                cand_p, cc_t, num_providers=P,
                eps_start=0.32, eps_end=0.02, threads=t,
                price=price, retired=retired,
                seed_provider_for_task=p4t,
                max_release=64, repair_mask=mask, outcomes=outs,
            )
            trace += [p4t.copy(), price.copy(),
                      outs["codes"].copy(), outs["margin"].copy()]
        chains[t] = trace
    _assert_identical(chains, "auction_sparse_mt warm chain")

    # --- stress 2b: the PARALLEL margin/certificate post-pass. The
    # helper pool only exists at T >= 8192 (kParMin), so the chunked
    # cert reduction + relaxed-atomic reach marks never run above; this
    # drives them at pool scale with stats on (cert scalars must be
    # bit-identical: fixed chunks summed in chunk order)
    Pq = Tq = max(8192, P)
    epq, erq, wq = _synth_marketplace(np.random.default_rng(23), Pq, Tq)
    cq_p, cq_c = native.fused_topk_candidates(
        epq, erq, wq, k=args.top_k, threads=max(threads)
    )
    certs = {}
    for t in threads:
        outs, stats = {}, {}
        p4t, price, _ = native.auction_sparse_mt(
            cq_p, cq_c, num_providers=Pq, threads=t,
            stats=stats, outcomes=outs,
        )
        certs[t] = [
            p4t.copy(), outs["codes"].copy(), outs["margin"].copy(),
            np.array([stats["plan_cost"], stats["idle_price"],
                      stats["cs_slack"]]),
        ]
    _assert_identical(certs, "auction_sparse_mt parallel cert pass")

    # --- stress 3: sparse Sinkhorn potentials (row updates + CSR-transpose
    # column updates), cold anneal then churned warm single-phase
    sink = {}
    for t in threads:
        crng = np.random.default_rng(13)
        cc_t = cand_c.copy()
        f, g = native.sinkhorn_sparse_anneal(
            cand_p, cc_t, P, eps_start=1.0, eps_end=0.05,
            iters_per_phase=30, threads=t,
        )
        trace = [f.copy(), g.copy()]
        for _ in range(args.ticks):
            rows = crng.choice(T, max(1, T // 50), replace=False)
            cc_t[rows] *= (0.8 + 0.4 * crng.random((rows.size, 1))).astype(np.float32)
            f, g, iters, err = native.sinkhorn_sparse_mt(
                cand_p, cc_t, P, eps=0.05, max_iters=40, threads=t, f=f, g=g,
            )
            trace += [f.copy(), g.copy()]
        sink[t] = trace
    _assert_identical(sink, "sinkhorn_sparse_mt warm chain")

    # --- stress 4: the full NativeSolveArena dirty-row pipeline (delta
    # candidate passes, merge change-detection, dual carry, dual refresh)
    from protocol_tpu.native.arena import NativeSolveArena

    arena_runs = {}
    for t in threads:
        crng = np.random.default_rng(17)
        ep_t, er_t, w_t = _synth_marketplace(np.random.default_rng(7), P, T)
        arena = NativeSolveArena(k=K, threads=t, dual_refresh_every=2)
        trace = [arena.solve(ep_t, er_t, w_t).copy()]
        for _ in range(args.ticks):
            _churn(crng, ep_t, er_t, frac=0.02)
            trace.append(arena.solve(ep_t, er_t, w_t).copy())
        arena_runs[t] = trace
    _assert_identical(arena_runs, "NativeSolveArena warm churn")

    # cross-ISA evidence for the parent: the two vector ISAs share one
    # fmaf-matched pipeline, so their plans must be bit-identical — the
    # parent compares this digest between the avx2 and avx512 children
    import hashlib

    h = hashlib.sha256()
    for arr in (cand_p, cand_c, *repair_runs[threads[0]]):
        h.update(np.ascontiguousarray(arr).tobytes())
    print(f"[child] PLAN-DIGEST isa={effective} {h.hexdigest()}")

    print(f"[child] OK: all kernels thread-invariant over threads={threads}")
    return 0


# --------------------------------------------------------------- parent

def _runtime_so(variant_so: str, name: str) -> str:
    """Resolve the sanitizer runtime the instrumented .so links against
    (``ldd`` output line ``libtsan.so.0 => /path (...)``) — the LD_PRELOAD
    value that puts the runtime first in the child's link order."""
    out = subprocess.run(
        ["ldd", variant_so], capture_output=True, text=True, check=True
    ).stdout
    for line in out.splitlines():
        if name in line and "=>" in line:
            path = line.split("=>")[1].split("(")[0].strip()
            if path and os.path.exists(path):
                return path
    raise SystemExit(
        f"cannot resolve {name} runtime from ldd {variant_so}; "
        "is the sanitizer toolchain installed?"
    )


def _scan_reports(log_dir: str) -> tuple[int, list[str]]:
    hits, excerpts = 0, []
    for fn in sorted(os.listdir(log_dir)):
        path = os.path.join(log_dir, fn)
        text = open(path, errors="replace").read()
        n = sum(text.count(m) for m in _REPORT_MARKERS)
        if n:
            hits += n
            excerpts.append(f"--- {fn} ({n} report(s)) ---\n{text[:4000]}")
    return hits, excerpts


def _clang_tidy(log) -> bool:
    tidy = shutil.which("clang-tidy")
    if tidy is None:
        # non-optional (ISSUE 10 satellite): absence FAILS — the old
        # skip-with-a-note behavior let the static half of the
        # discipline silently rot in any environment missing the
        # toolchain. CI installs a pinned clang-tidy; local runs
        # without it must opt out explicitly (--skip-clang-tidy).
        log(
            "clang-tidy: NOT on PATH — the static pass is mandatory "
            "(install clang-tidy, or pass --skip-clang-tidy to "
            "acknowledge the gap)"
        )
        return False
    version = subprocess.run(
        [tidy, "--version"], capture_output=True, text=True
    ).stdout.strip().splitlines()
    log(f"clang-tidy: {version[-1] if version else 'unknown version'}")
    proc = subprocess.run(
        [tidy, os.path.join(_REPO, "native", "assign_engine.cpp"),
         "--quiet", "--warnings-as-errors=*",
         "--", "-std=gnu++17", "-pthread"],
        capture_output=True, text=True, cwd=_REPO,
    )
    log(f"clang-tidy: rc={proc.returncode}")
    if proc.stdout.strip():
        log(proc.stdout[-6000:])
    return proc.returncode == 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--sanitizer", choices=("tsan", "asan"), default="tsan")
    ap.add_argument("--threads", default="1,2,4,8",
                    help="comma-separated thread counts to sweep")
    ap.add_argument("--providers", type=int, default=1024)
    ap.add_argument("--tasks", type=int, default=1024)
    ap.add_argument("--top-k", type=int, default=24)
    ap.add_argument("--ticks", type=int, default=3,
                    help="churned warm re-solves per thread count")
    ap.add_argument("--isas", default="auto",
                    help="comma-separated runtime ISAs to stress "
                         "(scalar,avx2,avx512), or 'auto' for every ISA "
                         "the host supports — one sanitized child per ISA")
    ap.add_argument("--artifact", default=None,
                    help="write the run log here (e.g. artifacts/sanitize_tsan.log)")
    ap.add_argument("--skip-clang-tidy", action="store_true")
    ap.add_argument("--tidy-only", action="store_true",
                    help="run only the mandatory clang-tidy static pass "
                         "(no sanitizer build/stress) — the per-PR CI step")
    ap.add_argument("--rebuild", action="store_true",
                    help="force a fresh sanitizer build even if current")
    ap.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    args = ap.parse_args()

    if args.child:
        return _child(args)

    lines: list[str] = []

    def log(msg: str) -> None:
        print(msg)
        lines.append(msg)

    if args.tidy_only:
        ok = _clang_tidy(log)
        log(f"VERDICT: {'PASS' if ok else 'FAIL'} (clang-tidy only)")
        return 0 if ok else 1

    from protocol_tpu import native

    t0 = time.time()
    log(f"sanitize_native: sanitizer={args.sanitizer} "
        f"threads={args.threads} P={args.providers} T={args.tasks} "
        f"k={args.top_k} ticks={args.ticks}")
    so = native.so_path(args.sanitizer)
    if (
        args.rebuild
        or not os.path.exists(so)
        or os.path.getmtime(so) < os.path.getmtime(native._SRC)
    ):
        # same staleness rule as native.load(); CI restores a cached .so
        # keyed on the source hash and touches it fresh, so steady-state
        # runs skip the compile
        native.build(args.sanitizer)
    log(f"using {os.path.relpath(so, _REPO)} "
        f"({os.path.getsize(so)} bytes, flags: "
        f"{' '.join(native._cflags(args.sanitizer))})")

    runtime_name = {"tsan": "libtsan", "asan": "libasan"}[args.sanitizer]
    runtime = _runtime_so(so, runtime_name)
    log(f"LD_PRELOAD runtime: {runtime}")

    # one sanitized child per runtime ISA: the env var forces the
    # dispatch, so the vector lane kernels / block-skip survivors / tiled
    # sweeps execute under the instrumentation, not just the scalar path
    if args.isas == "auto":
        isas = ["scalar"]
        for name in ("avx2", "avx512"):
            if native.isa_supported(name):
                isas.append(name)
    else:
        isas = [s.strip() for s in args.isas.split(",") if s.strip()]
        for name in isas:
            if name not in ("scalar", "avx2", "avx512"):
                raise SystemExit(f"unknown --isas entry {name!r}")
    log(f"runtime ISAs under stress: {isas}")

    ok = True
    digests: dict[str, str] = {}
    for isa in isas:
        with tempfile.TemporaryDirectory(prefix="sanitize_native_") as log_dir:
            prefix = os.path.join(log_dir, "report")
            env = dict(os.environ)
            env["PROTOCOL_TPU_NATIVE_SANITIZE"] = args.sanitizer
            env["PROTOCOL_TPU_NATIVE_ISA"] = isa
            env["LD_PRELOAD"] = runtime
            common = f"log_path={prefix}:exitcode={_SAN_EXITCODE}"
            env["TSAN_OPTIONS"] = f"{common}:second_deadlock_stack=1"
            # detect_leaks=0: CPython "leaks" by design (interned objects,
            # static allocations); leak noise would bury real engine reports
            env["ASAN_OPTIONS"] = f"{common}:detect_leaks=0"
            env["UBSAN_OPTIONS"] = f"{common}:print_stacktrace=1"
            cmd = [
                sys.executable, os.path.abspath(__file__), "--child",
                "--sanitizer", args.sanitizer, "--threads", args.threads,
                "--providers", str(args.providers), "--tasks", str(args.tasks),
                "--top-k", str(args.top_k), "--ticks", str(args.ticks),
            ]
            proc = subprocess.run(
                cmd, env=env, cwd=_REPO, capture_output=True, text=True
            )
            for stream in (proc.stdout, proc.stderr):
                if stream.strip():
                    log(stream.rstrip())
            for line in proc.stdout.splitlines():
                if "PLAN-DIGEST" in line:
                    digests[isa] = line.rsplit(" ", 1)[-1]
            hits, excerpts = _scan_reports(log_dir)
            log(f"child[isa={isa}] rc={proc.returncode}, sanitizer "
                f"reports={hits}, wall={time.time() - t0:.1f}s")
            for e in excerpts:
                log(e)
            if proc.returncode != 0 or hits:
                ok = False

    # shared-pipeline contract: avx2 and avx512 run one fmaf-matched
    # float pipeline, so their candidate plans must be bit-identical
    # (the scalar referee is allowed its documented float tolerance)
    if "avx2" in digests and "avx512" in digests:
        if digests["avx2"] != digests["avx512"]:
            log("CROSS-ISA MISMATCH: avx2 and avx512 plan digests differ "
                "(shared-pipeline contract broken)")
            ok = False
        else:
            log("cross-ISA: avx2 == avx512 plan digests bit-identical")

    if not args.skip_clang_tidy and not _clang_tidy(log):
        ok = False

    log(f"VERDICT: {'PASS' if ok else 'FAIL'} ({args.sanitizer})")
    if args.artifact:
        path = os.path.join(_REPO, args.artifact)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as fh:
            fh.write("\n".join(lines) + "\n")
        print(f"artifact written: {args.artifact}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
