"""Repo tooling package (makes ``python -m scripts.lints`` importable)."""
