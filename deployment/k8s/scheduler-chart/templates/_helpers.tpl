{{- define "sched.fullname" -}}
{{ .Chart.Name }}
{{- end -}}
