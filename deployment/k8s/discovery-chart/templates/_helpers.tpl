{{- define "disc.fullname" -}}
{{ .Chart.Name }}-{{ .Values.computePoolId }}
{{- end -}}
