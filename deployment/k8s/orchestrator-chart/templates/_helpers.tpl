{{- define "orch.fullname" -}}
{{ .Chart.Name }}-{{ .Values.computePoolId }}
{{- end -}}
