{{- define "kv.fullname" -}}
{{ .Chart.Name }}
{{- end -}}
