{{- define "val.fullname" -}}
{{ .Chart.Name }}-{{ .Values.computePoolId }}
{{- end -}}
