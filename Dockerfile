# Service image for the Helm charts: one image, five entry points
# (python -m protocol_tpu.serve {discovery,orchestrator,validator,scheduler,worker}).
# The scheduler pod additionally needs the TPU-enabled jax wheel; override
# JAX_SPEC at build time for TPU node pools.
ARG PYTHON_VERSION=3.12
FROM python:${PYTHON_VERSION}-slim AS build

ARG JAX_SPEC="jax[cpu]"
RUN apt-get update && apt-get install -y --no-install-recommends g++ make \
    && rm -rf /var/lib/apt/lists/*
WORKDIR /app
COPY Makefile ./
COPY native ./native
RUN make native
COPY protocol_tpu ./protocol_tpu
RUN pip install --no-cache-dir "${JAX_SPEC}" aiohttp grpcio protobuf \
    cryptography numpy prometheus_client

FROM python:${PYTHON_VERSION}-slim
ARG PYTHON_VERSION
ARG VERSION=dev
ENV PROTOCOL_TPU_VERSION=${VERSION} \
    PYTHONUNBUFFERED=1
# docker CLI for the containerized task runtime (worker pods mount the
# host's docker socket or run dind); control-plane pods just don't use it
RUN apt-get update && apt-get install -y --no-install-recommends docker.io \
    && rm -rf /var/lib/apt/lists/*
WORKDIR /app
COPY --from=build /usr/local/lib/python${PYTHON_VERSION}/site-packages /usr/local/lib/python${PYTHON_VERSION}/site-packages
COPY --from=build /app/protocol_tpu ./protocol_tpu
COPY --from=build /app/native/libassign_engine.so ./native/libassign_engine.so
ENTRYPOINT ["python", "-m", "protocol_tpu.serve"]
